"""Declarative SLO/health alert engine (ISSUE 16).

Five rules evaluated against live ``/status`` snapshots and fleet
aggregation — the "actuator" side of the PR 13–15 sensors:

- ``slo_burn_rate``   — multi-window burn rate over the violation-rate
  counters PR 14 already exports: the fast (5 m) AND slow (1 h)
  trailing windows must BOTH exceed the SLO budget before the page
  fires. The AND-gate is the standard two-window construction: the
  slow window proves the burn is sustained (no page on one bad
  minute), the fast window proves it is still happening (no page an
  hour after recovery). An empty or single-sample window never fires.
- ``hbm_headroom``    — the tightest replica's free-HBM fraction shrank
  under the PR 15 budget line.
- ``goodput_drop``    — the run's goodput-so-far fraction fell under
  the floor after the run settled (steps > 0).
- ``health_collapse`` — the fleet's worst replica health score fell
  under the floor.
- ``stale_replicas``  — replicas stopped answering /status.
- ``reroute_spike``   — (ISSUE 17) the front-door router's reroute
  rate (reroutes / completed requests over the fast window, from the
  same cumulative-counter construction as the burn gate) exceeded
  ``TPUFLOW_ALERT_REROUTE_RATE``. Occasional reroutes are the router
  doing its job; a sustained rate means replicas are dying or
  stalling faster than the fleet absorbs.
- ``ttft_router_dominance`` — (ISSUE 18) the router-side admission
  wait per completed request over the fast window (the cumulative
  ``router_wait_s`` / ``router_requests`` counters through the same
  ``window_rate`` construction) exceeded
  ``TPUFLOW_ALERT_ROUTER_TTFT_FRAC`` of the fleet TTFT p95: the fleet
  is slow because requests sit in the ROUTER, not in the replicas —
  the exact split ``python -m tpuflow.obs trace`` shows per request.

Lifecycle: a rule entering its firing condition emits ONE
``alert.fired`` event (severity + runbook anchor + message); while it
stays firing, nothing more is emitted (dedup). When the condition
clears, ``alert.resolved`` is emitted — but only after the alert has
been active for ``TPUFLOW_ALERT_COOLDOWN_S`` (anti-flap hold: a
condition oscillating faster than the cooldown stays one alert).

Everything here is host-pure and jax-free: the engine consumes snapshot
dicts, the clock is injectable, and tests drive exact fired/resolved
sequences with zero device work (``compile_stats()`` unchanged on the
shared warmed engine is pinned in tests/test_serve.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs

# Severity ladder (ordered): page > ticket > info.
SEVERITIES = ("page", "ticket", "info")


@dataclass(frozen=True)
class Rule:
    """One declarative alert rule: identity, severity, and the README
    runbook anchor an operator lands on. The firing conditions live in
    ``AlertEngine._evaluate`` — they need engine state (counter
    windows), the rows here are the operator-facing contract."""

    name: str
    severity: str
    runbook: str
    doc: str


RULES: tuple[Rule, ...] = (
    Rule(
        "slo_burn_rate", "page", "regression--alerting-runbook",
        "fast AND slow trailing windows both burning the SLO "
        "violation-rate budget",
    ),
    Rule(
        "hbm_headroom", "page", "device-observatory-runbook",
        "tightest replica's free-HBM fraction under the budget line",
    ),
    Rule(
        "goodput_drop", "ticket", "goodput--live-monitoring-runbook",
        "goodput-so-far fraction under the floor after the run settled",
    ),
    Rule(
        "health_collapse", "page", "fleet-observability-runbook",
        "worst replica health score under the floor",
    ),
    Rule(
        "stale_replicas", "ticket", "fleet-observability-runbook",
        "one or more replicas stopped answering /status",
    ),
    Rule(
        "reroute_spike", "ticket", "router--failover-runbook",
        "front-door reroute rate over the fast window past the "
        "threshold — replicas dying/stalling faster than the fleet "
        "absorbs",
    ),
    Rule(
        "ttft_router_dominance", "ticket", "distributed-tracing-runbook",
        "router-side wait per request over the fast window exceeds the "
        "knob-set fraction of fleet TTFT p95 — latency lives in the "
        "router, not the replicas",
    ),
)


# ------------------------------------------------- burn-rate math (pure)
def window_rate(
    samples: list[tuple[float, float, float]],
    now: float,
    window_s: float,
) -> float | None:
    """Violation rate over the trailing window of cumulative
    ``(ts, requests, violations)`` counter samples. None — "cannot
    judge", which never fires — when the window holds fewer than two
    samples or no request flowed across it. Counter resets (a replica
    restart) clamp to 0 instead of going negative."""
    if window_s <= 0:
        return None
    cut = now - window_s
    win = [s for s in samples if s[0] >= cut]
    if len(win) < 2:
        return None
    d_req = win[-1][1] - win[0][1]
    d_vio = win[-1][2] - win[0][2]
    if d_req <= 0:
        return None
    return max(d_vio, 0.0) / d_req


def burn_gate(
    samples: list[tuple[float, float, float]],
    now: float,
    fast_s: float,
    slow_s: float,
    budget: float,
) -> tuple[bool, dict[str, Any]]:
    """The two-window AND-gate: fires iff BOTH the fast and the slow
    trailing windows' violation rates exceed ``budget``. Either window
    empty/short → never fires (property-tested)."""
    fast = window_rate(samples, now, fast_s)
    slow = window_rate(samples, now, slow_s)
    detail: dict[str, Any] = {
        "fast_rate": fast, "slow_rate": slow, "budget": budget,
    }
    if fast is None or slow is None or budget <= 0:
        return False, detail
    detail["fast_burn"] = round(fast / budget, 3)
    detail["slow_burn"] = round(slow / budget, 3)
    return fast > budget and slow > budget, detail


class AlertEngine:
    """Evaluate the declarative rules against snapshots, with
    deduplicated fired/resolved lifecycle events.

    ``clock`` is injectable (tests pin exact sequences); thresholds
    default from the ``TPUFLOW_ALERT_*`` knobs. ``observe()`` is safe
    from the export server's handler threads."""

    def __init__(
        self,
        *,
        rules: tuple[Rule, ...] = RULES,
        clock: Callable[[], float] = time.monotonic,
        slo_budget: float | None = None,
        fast_window_s: float | None = None,
        slow_window_s: float | None = None,
        hbm_headroom: float | None = None,
        goodput_min: float | None = None,
        min_health: float | None = None,
        cooldown_s: float | None = None,
        reroute_rate: float | None = None,
        router_ttft_frac: float | None = None,
    ):
        self.rules = {r.name: r for r in rules}
        self._clock = clock
        if slo_budget is None:
            slo_budget = knobs.get_float("TPUFLOW_ALERT_SLO_BUDGET")
        if fast_window_s is None:
            fast_window_s = knobs.get_float("TPUFLOW_ALERT_FAST_WINDOW_S")
        if slow_window_s is None:
            slow_window_s = knobs.get_float("TPUFLOW_ALERT_SLOW_WINDOW_S")
        if hbm_headroom is None:
            hbm_headroom = knobs.get_float("TPUFLOW_ALERT_HBM_HEADROOM")
        if goodput_min is None:
            goodput_min = knobs.get_float("TPUFLOW_ALERT_GOODPUT_MIN")
        if min_health is None:
            min_health = knobs.get_float("TPUFLOW_ALERT_MIN_HEALTH")
        if cooldown_s is None:
            cooldown_s = knobs.get_float("TPUFLOW_ALERT_COOLDOWN_S")
        if reroute_rate is None:
            reroute_rate = knobs.get_float("TPUFLOW_ALERT_REROUTE_RATE")
        if router_ttft_frac is None:
            router_ttft_frac = knobs.get_float(
                "TPUFLOW_ALERT_ROUTER_TTFT_FRAC"
            )
        self.slo_budget = float(slo_budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.hbm_headroom = float(hbm_headroom)
        self.goodput_min = float(goodput_min)
        self.min_health = float(min_health)
        self.cooldown_s = float(cooldown_s)
        self.reroute_rate = float(reroute_rate)
        self.router_ttft_frac = float(router_ttft_frac)
        self._samples: deque[tuple[float, float, float]] = deque()
        # (ts, router_requests, router_reroutes) — same cumulative-
        # counter shape as _samples, so window_rate() applies verbatim.
        self._router_samples: deque[tuple[float, float, float]] = deque()
        # (ts, router_requests, router_wait_s): window_rate() over it
        # is mean router-side wait per completed request — the
        # ttft_router_dominance numerator.
        self._router_wait_samples: deque[
            tuple[float, float, float]
        ] = deque()
        self._active: dict[str, dict[str, Any]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------ evaluation
    def _feed_counters(
        self, now: float, status: dict | None, fleet: dict | None
    ) -> None:
        req = vio = None
        if status is not None:
            req = status.get("serve_requests")
            vio = status.get("serve_slo_violations")
        if req is None and fleet is not None:
            req = fleet.get("requests")
            vio = fleet.get("slo_violations")
        if isinstance(req, (int, float)) and isinstance(
            vio, (int, float)
        ):
            self._samples.append((now, float(req), float(vio)))
            cut = now - self.slow_window_s
            while self._samples and self._samples[0][0] < cut:
                self._samples.popleft()
        rr = rq = None
        if status is not None:
            rq = status.get("router_requests")
            rr = status.get("router_reroutes")
        if isinstance(rq, (int, float)) and isinstance(
            rr, (int, float)
        ):
            self._router_samples.append((now, float(rq), float(rr)))
            cut = now - max(self.fast_window_s, self.slow_window_s)
            while (
                self._router_samples
                and self._router_samples[0][0] < cut
            ):
                self._router_samples.popleft()
        rw = status.get("router_wait_s") if status is not None else None
        if isinstance(rq, (int, float)) and isinstance(
            rw, (int, float)
        ):
            self._router_wait_samples.append(
                (now, float(rq), float(rw))
            )
            cut = now - max(self.fast_window_s, self.slow_window_s)
            while (
                self._router_wait_samples
                and self._router_wait_samples[0][0] < cut
            ):
                self._router_wait_samples.popleft()

    def _evaluate(
        self, now: float, status: dict | None, fleet: dict | None
    ) -> dict[str, tuple[str, Any]]:
        """rule name -> (message, value) for every rule firing NOW."""
        firing: dict[str, tuple[str, Any]] = {}
        burns, detail = burn_gate(
            list(self._samples), now, self.fast_window_s,
            self.slow_window_s, self.slo_budget,
        )
        if burns:
            firing["slo_burn_rate"] = (
                f"SLO burn: fast {detail['fast_burn']}x / slow "
                f"{detail['slow_burn']}x the "
                f"{self.slo_budget:.4g} violation-rate budget",
                detail.get("fast_rate"),
            )
        frac = None
        if status is not None and isinstance(
            status.get("hbm_used_frac"), (int, float)
        ):
            frac = float(status["hbm_used_frac"])
        if frac is None and fleet is not None and isinstance(
            fleet.get("hbm_used_frac_max"), (int, float)
        ):
            frac = float(fleet["hbm_used_frac_max"])
        if frac is not None and (1.0 - frac) < self.hbm_headroom:
            firing["hbm_headroom"] = (
                f"HBM headroom {1.0 - frac:.3f} under the "
                f"{self.hbm_headroom:.3f} budget line "
                f"(used {frac:.3f} of the tightest device)",
                round(1.0 - frac, 4),
            )
        if status is not None:
            gp = status.get("goodput_fraction")
            steps = status.get("steps", 0)
            if (
                isinstance(gp, (int, float))
                and isinstance(steps, (int, float))
                and steps > 0
                and gp < self.goodput_min
            ):
                firing["goodput_drop"] = (
                    f"goodput fraction {gp:.3f} under the "
                    f"{self.goodput_min:.2f} floor",
                    float(gp),
                )
        if fleet is not None:
            mh = fleet.get("min_health")
            if (
                isinstance(mh, (int, float))
                and fleet.get("replicas", 0)
                and mh < self.min_health
            ):
                firing["health_collapse"] = (
                    f"worst replica health {mh:.2f} under the "
                    f"{self.min_health:.2f} floor",
                    float(mh),
                )
            stale = fleet.get("stale")
            if isinstance(stale, (int, float)) and stale > 0:
                firing["stale_replicas"] = (
                    f"{int(stale)} replica(s) stale "
                    f"(of {fleet.get('replicas', '?')})",
                    int(stale),
                )
        rrate = window_rate(
            list(self._router_samples), now, self.fast_window_s
        )
        if rrate is not None and rrate > self.reroute_rate:
            firing["reroute_spike"] = (
                f"router reroute rate {rrate:.3f} over the fast "
                f"window exceeds the {self.reroute_rate:.3g} "
                f"threshold — replicas dying/stalling faster than "
                f"the fleet absorbs",
                round(rrate, 4),
            )
        # Mean router-side wait per completed request vs the fleet TTFT
        # p95: when the router's share crosses the knob-set fraction,
        # the latency lives in admission/queueing, not the replicas.
        wrate = window_rate(
            list(self._router_wait_samples), now, self.fast_window_s
        )
        p95 = None
        if fleet is not None and isinstance(fleet.get("ttft"), dict):
            p = fleet["ttft"].get("p95")
            if (
                isinstance(p, (int, float))
                and p > 0
                and p != float("inf")
            ):
                p95 = float(p)
        if (
            wrate is not None
            and p95 is not None
            and self.router_ttft_frac > 0
            and wrate > self.router_ttft_frac * p95
        ):
            firing["ttft_router_dominance"] = (
                f"router-side wait {wrate:.4f}s/request over the fast "
                f"window exceeds {self.router_ttft_frac:.3g}x the "
                f"fleet TTFT p95 ({p95:.4f}s) — latency lives in the "
                f"router; pull a trace: python -m tpuflow.obs trace "
                f"<request_id>",
                round(wrate, 6),
            )
        return {k: v for k, v in firing.items() if k in self.rules}

    # ------------------------------------------------------- lifecycle
    def observe(
        self,
        status: dict | None = None,
        fleet: dict | None = None,
    ) -> list[dict[str, Any]]:
        """One evaluation sweep. Returns the lifecycle transitions THIS
        sweep caused — ``{"state": "fired"|"resolved", ...}`` — each
        also emitted to the event stream. A rule already active stays
        silent while it keeps firing (dedup); a clearing rule resolves
        only after ``cooldown_s`` of activity (anti-flap)."""
        with self._lock:
            now = self._clock()
            self._feed_counters(now, status, fleet)
            firing = self._evaluate(now, status, fleet)
            transitions: list[dict[str, Any]] = []
            for name, rule in self.rules.items():
                hit = firing.get(name)
                st = self._active.get(name)
                if hit is not None and st is None:
                    message, value = hit
                    st = {
                        "rule": name,
                        "severity": rule.severity,
                        "runbook": rule.runbook,
                        "message": message,
                        "value": value,
                        "since": now,
                    }
                    self._active[name] = st
                    _rec.event(
                        "alert.fired",
                        rule=name,
                        severity=rule.severity,
                        runbook=rule.runbook,
                        message=message,
                        value=value,
                    )
                    transitions.append({"state": "fired", **st})
                elif hit is not None:
                    st["message"], st["value"] = hit
                elif st is not None:
                    if now - st["since"] >= self.cooldown_s:
                        del self._active[name]
                        _rec.event(
                            "alert.resolved",
                            rule=name,
                            severity=rule.severity,
                            runbook=rule.runbook,
                            active_s=round(now - st["since"], 3),
                        )
                        transitions.append(
                            {
                                "state": "resolved",
                                "active_s": round(now - st["since"], 3),
                                **st,
                            }
                        )
            return transitions

    def active(self) -> list[dict[str, Any]]:
        """Current active alerts (the /alerts endpoint body), severity-
        major order."""
        with self._lock:
            sev_rank = {s: i for i, s in enumerate(SEVERITIES)}
            return sorted(
                (dict(a) for a in self._active.values()),
                key=lambda a: (
                    sev_rank.get(a["severity"], len(SEVERITIES)),
                    a["rule"],
                ),
            )

    def describe(self) -> list[dict[str, str]]:
        """The rule table (the /alerts endpoint's ``rules`` section and
        the README runbook's source of truth)."""
        return [
            {
                "rule": r.name,
                "severity": r.severity,
                "runbook": r.runbook,
                "doc": r.doc,
            }
            for r in self.rules.values()
        ]


def format_transition(t: dict[str, Any]) -> str:
    """One ALERT line for tpu_watch --follow/--fleet."""
    if t["state"] == "fired":
        return (
            f"ALERT [{t['severity']}] {t['rule']} FIRED: {t['message']} "
            f"(runbook #{t['runbook']})"
        )
    return (
        f"ALERT [{t['severity']}] {t['rule']} RESOLVED "
        f"after {t.get('active_s', 0.0):.1f}s"
    )


_ENGINE: AlertEngine | None = None
_ENGINE_LOCK = threading.Lock()


def engine() -> AlertEngine:
    """The process's shared alert engine (the export server's /alerts
    route evaluates it against each /status snapshot it serves)."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = AlertEngine()
        return _ENGINE


def reset() -> None:
    """Drop the shared engine (tests)."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = None
