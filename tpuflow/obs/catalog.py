"""Canonical catalog of telemetry names.

Every span/counter/gauge/histogram/event name emitted anywhere in tpuflow
is registered here, once, with its kind and meaning. The catalog is the
contract between emitters (runner/trainer/ckpt/data/infer) and consumers
(the timeline card, ``obs.summarize``, downstream flows reading a run's
telemetry): names can't silently drift between the two sides because
``tools/obs_lint.py`` (and its pytest twin) greps every literal emitter
call in the tree and fails on any name missing from this table.

Kinds:

- ``span``      — a timed region: one event with ``ts`` (wall-clock start)
                  and ``dur_s`` (monotonic duration).
- ``counter``   — a monotonically accumulated amount (sum over the run).
- ``gauge``     — a sampled instantaneous value (last/max are meaningful,
                  sums are not).
- ``histogram`` — raw observations; consumers compute count/mean/p50/max.
- ``event``     — a point-in-time record (warnings, markers, reports).
"""

from __future__ import annotations

CATALOG: dict[str, tuple[str, str]] = {
    # ---------------------------------------------------------------- flow
    "flow.run": ("span", "one whole flow run, start → terminal status"),
    "flow.step": ("span", "one step/task execution (attempt granularity)"),
    "flow.gang": ("span", "gang execution: members launched → all joined"),
    "flow.gang_member": ("span", "one gang member process's step body"),
    "flow.retry": ("counter", "step attempts that failed and were retried"),
    "flow.retry_backoff_s": (
        "gauge",
        "jittered exponential backoff slept before a retry attempt",
    ),
    "flow.member_failed": (
        "event",
        "gang supervisor: first non-zero member exit (member, rc, log "
        "tail); surviving peers are killed promptly",
    ),
    "flow.heartbeat_stall": (
        "event",
        "gang supervisor: a member's heartbeat went silent past the stall "
        "timeout (member, age_s, last_step — heartbeats stamp the current "
        "step, so the report says WHERE the member stalled, not just how "
        "long ago); the gang is killed",
    ),
    "flow.preempt": (
        "event",
        "a gang member exited with the requeue code after a preemption "
        "drain; the step reruns without consuming the retry budget",
    ),
    # Elastic gang (ISSUE 7): member loss becomes a mesh resize at
    # step-fence granularity instead of a requeue-the-world.
    "flow.member_lost": (
        "event",
        "elastic supervisor: a gang member died (member, rc, log tail, "
        "flight, survivor count) and the gang SHRINKS over the survivors "
        "instead of failing the step — contrast flow.member_failed, the "
        "fail-fast path",
    ),
    "flow.gang_resize": (
        "span",
        "one mesh re-form, announce → all survivors joined the new "
        "generation (generation, kind=shrink|grow, from/to member "
        "counts); feeds the goodput ledger's `resize` bucket",
    ),
    "flow.card_render": ("span", "card HTML render at step completion"),
    # --------------------------------------------------------------- train
    "train.fit": ("span", "Trainer.fit: mesh build + worker loop + drain"),
    "train.epoch": ("span", "one training epoch (steady-state steps only)"),
    "train.compile": ("span", "first-step jit trace + compile, fenced"),
    "train.step_s": ("histogram", "steady-state per-step wall time, fenced"),
    "train.validation": ("span", "held-out validation pass"),
    "train.tokens": ("counter", "steady-state tokens consumed by train steps"),
    "train.report": ("event", "one TrainContext.report: step + metrics"),
    "train.dispatch_depth": (
        "gauge",
        "dispatch-ahead window depth the hot loop resolved "
        "(TPUFLOW_DISPATCH_DEPTH): how many steps may be in flight "
        "before the host settles the oldest step's scalars",
    ),
    # Raise-MFU step work (ISSUE 10): remat-selector provenance and the
    # comm/compute roofline attribution pair.
    "train.remat_policy": (
        "event",
        "resolved remat selector for the leg (none|full|dots|policy "
        "name; TPUFLOW_REMAT_POLICY beats the config) plus whether the "
        "comm-overlapped accumulation scan is armed — the run's "
        "memory/recompute/overlap trade, auditable from the stream",
    ),
    "train.exposed_comm_s": (
        "gauge",
        "per-step seconds NOT at peak compute (mean epoch step wall − "
        "6·N·tokens/peak): an UPPER bound on exposed communication — "
        "memory stalls and bubbles charge here too, keeping the overlap "
        "claim conservative (train.step.comm_attribution; TPU only)",
    ),
    "train.comm_overlap_s": (
        "gauge",
        "per-step seconds of FSDP collective time hidden behind compute "
        "(comm roofline − exposed_comm_s, floored at 0): a LOWER bound "
        "on overlapped comm (train.step.comm_attribution; TPU only)",
    ),
    # ---------------------------------------------------------------- ckpt
    "ckpt.save": ("span", "checkpoint save, save() → commit; bytes + gbps"),
    "ckpt.restore": ("span", "checkpoint restore; bytes + gbps when known"),
    "ckpt.verify": (
        "event",
        "explicit integrity audit of one step (verify_step): shard count "
        "checked + outcome",
    ),
    "ckpt.corrupt": (
        "event",
        "a shard failed crc32/truncation verification; restore fell back "
        "to the next tier / previous committed step or raised — never "
        "silent",
    ),
    # Durable checkpointing under storage failure (ISSUE 5): retrying I/O,
    # staged atomic commits + GC, the local fast tier, emergency saves.
    "ckpt.io_retry": (
        "event",
        "one transient storage error absorbed by the retrying I/O wrapper "
        "(op, path, attempt, jittered backoff slept)",
    ),
    "ckpt.io_error": (
        "event",
        "a storage operation failed for good: permanent errno or retry "
        "budget exhausted (raises CheckpointIOError)",
    ),
    "ckpt.save_failed": (
        "event",
        "one step's save died on a classified storage error after "
        "retries: staging reclaimed, history entry dropped, training "
        "continues on the previous committed step — never a member death",
    ),
    "ckpt.gc": (
        "event",
        "manager startup reclaimed killed-writer leftovers: staged .tmp "
        "dirs, uncommitted step dirs, stale local-tier staging/overflow",
    ),
    "ckpt.upload": (
        "span",
        "local fast tier → persistent run dir copy of one committed step "
        "(async saver thread); ok=False means the step is durable locally "
        "only",
    ),
    "ckpt.restore_tier": (
        "event",
        "which tier served a restore (local | persistent) for which step "
        "— the fallback-ladder evidence trail",
    ),
    "ckpt.emergency_save": (
        "event",
        "last-chance synchronous commit on the fastest tier inside a "
        "closing preemption-grace window (upload skipped); the requeued "
        "attempt resumes from this step",
    ),
    # ---------------------------------------------------------------- data
    "data.batch_wait_s": ("histogram", "time the consumer blocked per batch"),
    "data.prefetch_hit": ("counter", "batches ready with no consumer wait"),
    "data.prefetch_miss": ("counter", "batches the consumer had to wait for"),
    "data.host_wait_s": (
        "gauge",
        "seconds the consuming loop actually blocked for this batch "
        "(~0 on every prefetch hit = the input pipeline ran entirely "
        "behind device compute)",
    ),
    # --------------------------------------------------------------- infer
    "infer.predict": ("span", "BatchPredictor forward over one batch"),
    "infer.generate": ("span", "one generate() call; tokens + tokens/s"),
    "infer.generate_batch": ("span", "GenerationPredictor batch decode"),
    "infer.spec.forwards": ("counter", "speculative verify forwards"),
    "infer.spec.committed": ("counter", "tokens committed by speculation"),
    "infer.spec.acceptance": ("gauge", "realized tokens per verify forward"),
    # --------------------------------------------------------------- serve
    # Continuous-batching serving engine (ISSUE 8): request-level
    # telemetry from tpuflow.infer.serve, also mirrored live on the
    # /metrics exporter via the process ledger's serve_* snapshot keys.
    "serve.warmup": (
        "span",
        "AOT warm pass at server start: decode program + insert + every "
        "prefill bucket compiled-or-cache-loaded once (carries the jit "
        "cache sizes — the never-recompile baseline)",
    ),
    "serve.prefill": (
        "span",
        "one admission: chunked prefill of a request's prompt at its "
        "bucket width, fenced on the first generated token",
    ),
    "serve.decode": (
        "span",
        "one decode block of the persistent slot-based program "
        "(decode_block tokens per live slot, one host sync)",
    ),
    "serve.admit": (
        "event",
        "a queued request entered a free slot (request, slot, bucket, "
        "queue_wait_s)",
    ),
    "serve.complete": (
        "event",
        "a request finished (tokens, reason=eos|budget|capacity, "
        "ttft_s, decode_tokens_per_s)",
    ),
    "serve.queue_depth": (
        "gauge", "requests waiting for a free slot (sampled per iteration)"
    ),
    "serve.slot_occupancy": (
        "gauge", "live fraction of the engine's fixed decode slots"
    ),
    "serve.ttft_s": (
        "gauge",
        "one request's submit → first-token latency (queue wait + "
        "bucketed prefill)",
    ),
    "serve.tokens_per_s": (
        "gauge",
        "one completed request's post-first-token decode rate (its slot's "
        "share of the batched decode program)",
    ),
    "serve.tokens": ("counter", "generated tokens served by the engine"),
    "serve.requests": ("counter", "requests completed by the engine"),
    # Paged KV serving (ISSUE 11): page-pool headroom, shared-prefix
    # reuse, speculative acceptance, and the eviction evidence trail —
    # the gauges the Serving runbook's paged section reads, mirrored as
    # tpuflow_serve_* names on /metrics.
    "serve.pages_free": (
        "gauge",
        "KV pages allocatable right now (truly free + idle prefix-cache "
        "pages reclaimable by eviction); admission blocks — queues, "
        "never drops — when a request's page need exceeds this",
    ),
    "serve.prefix_hits": (
        "gauge",
        "cumulative prompt pages served from the shared-prefix cache "
        "instead of being allocated + recomputed into a private copy "
        "(refcounted page reuse across requests)",
    ),
    "serve.spec_accept_rate": (
        "gauge",
        "cumulative speculative tokens committed per per-row verify "
        "(1.0 = speculation buys nothing; draft_len + 1 is the ceiling)",
    ),
    "serve.page_evict": (
        "event",
        "pool pressure reclaimed an idle (refcount-0) prefix-cache page "
        "LRU-first; its cached prefix must be recomputed on next use",
    ),
    # Disaggregated prefill/decode + tiered KV (ISSUE 19): committed
    # page sets ship prefill→decode through kv_store, and evicted
    # prefix pages spill HBM → host DRAM → node-local disk instead of
    # being forgotten. All host-side; compile_stats() is unchanged.
    "serve.kv_ship": (
        "span",
        "prefill-role export: chunked prefill + page extraction + the "
        "atomic kv_store commit of one KVPageSet (prompt_len, pages, "
        "key) — the prefill half of a disaggregated admission",
    ),
    "serve.kv_import": (
        "span",
        "decode-role import: load + validate a committed KVPageSet at "
        "submit (ok=False = torn/missing/mismatched set → the request "
        "rides local prefill; the fallback evidence the chaos tests "
        "assert)",
    ),
    "serve.tier_hit": (
        "event",
        "a prompt's prefix-digest chain matched pages in a lower tier "
        "(host/disk counts); the pages promote back into the HBM pool "
        "instead of being recomputed by prefill",
    ),
    "serve.tier_promote": (
        "event",
        "tier-hit pages were written back into the HBM pool for an "
        "admission (pages, and whether prefill was skipped entirely)",
    ),
    "serve.tier_spill": (
        "event",
        "an evicted prefix page's content dropped to a lower tier "
        "(tier=host|disk) instead of being forgotten — still findable "
        "through the bounded digest→tier index",
    ),
    "serve.pages_host": (
        "gauge",
        "prefix pages currently held by the host-DRAM spill tier "
        "(TPUFLOW_KV_HOST_MB budget, LRU)",
    ),
    "serve.pages_disk": (
        "gauge",
        "prefix pages findable in the node-local disk spill tier "
        "(TPUFLOW_KV_DISK_DIR; survives engine restarts)",
    ),
    # Serving observatory (ISSUE 13): per-request lifecycle traces, the
    # engine-time ledger fractions, and declared-SLO accounting — the
    # serving analog of the goodput ledger (tpuflow.obs.serve_ledger),
    # read by `python -m tpuflow.obs serve-summary` and the timeline
    # card's Serving section.
    "serve.trace": (
        "event",
        "one request-lifecycle transition (request, phase=submitted|"
        "queued|admitted|first_token|tick|complete|drained, plus the "
        "phase's evidence: backpressure reason, bucket/pages, ttft_s, "
        "tokens committed per tick, finish reason); the full per-request "
        "record also lands in the obs/ access log",
    ),
    "serve.slo_violation": (
        "event",
        "a request violated a declared latency SLO (slo=ttft|itl, "
        "value, limit_s, group; armed by TPUFLOW_SERVE_SLO_TTFT_MS / "
        "TPUFLOW_SERVE_SLO_ITL_MS)",
    ),
    "serve.slo_violations": (
        "counter",
        "cumulative declared-SLO violations (TTFT + ITL) — the number "
        "tpu_watch --follow and /metrics surface",
    ),
    "serve.idle_fraction": (
        "gauge",
        "engine-time ledger: fraction of serve wall spent in the idle "
        "sleep (nothing queued, nothing live) — high idle with low "
        "queue depth means the replica is over-provisioned",
    ),
    "serve.decode_fraction": (
        "gauge",
        "engine-time ledger: fraction of serve wall inside the decode "
        "(+ verify) device dispatches — the bucket that earns tokens",
    ),
    "serve.prefill_fraction": (
        "gauge",
        "engine-time ledger: fraction of serve wall inside admission "
        "prefill dispatches — high under churny short-request traffic",
    ),
    "serve.decode_utilization": (
        "gauge",
        "occupancy-weighted decode utilization: live rows / batch rows "
        "summed over dispatched blocks (1.0 = every row of every block "
        "earned its FLOPs; low values say raise arrival rate or shrink "
        "slots)",
    ),
    "serve.masked_row_waste": (
        "gauge",
        "fraction of dispatched batch rows live engine-wide but masked "
        "OUT of the dispatching group's program — what the "
        "(fp,int8)x(spec,plain) partition costs on mixed traffic",
    ),
    # Per-request int8 serving (ISSUE 9): the quantized twin of the
    # persistent decode program, plus the completion trail that lets an
    # operator split throughput by numeric path.
    "serve.quant_decode": (
        "span",
        "one decode block of the INT8 (fused-native W8A8) persistent "
        "program over the quantize=True slots — runs beside serve.decode "
        "when fp and int8 requests share the engine",
    ),
    "serve.quant_requests": (
        "counter",
        "completed requests that decoded through the int8 path (subset "
        "of serve.requests)",
    ),
    # --------------------------------------------------------------- fleet
    # Fleet observatory (ISSUE 14): replica discovery/registration, the
    # cross-replica poll sweep, and the staleness evidence trail —
    # emitted by tpuflow.obs.fleet / tpuflow.obs.export, read by
    # `python -m tpuflow.obs fleet-summary`, `tpu_watch --fleet`, and
    # the timeline card's Fleet section.
    "fleet.register": (
        "event",
        "this replica stamped its registration file into "
        "TPUFLOW_FLEET_REGISTRATION_DIR at export start (url, replica "
        "id, path) — how a fleet observatory discovers it without a "
        "static URL list",
    ),
    "fleet.poll": (
        "span",
        "one fleet poll sweep: discover replicas, poll every /status "
        "with per-replica timeout/backoff, aggregate one fleet snapshot",
    ),
    "fleet.size": (
        "gauge",
        "replicas the fleet observatory currently tracks (carries "
        "healthy= — the count with health score >= 0.5 and fresh "
        "/status)",
    ),
    "fleet.qps": (
        "gauge",
        "fleet aggregate completed-requests/s, summed from per-replica "
        "completion-counter deltas between successful polls",
    ),
    "fleet.replica_stale": (
        "event",
        "a replica aged past TPUFLOW_FLEET_STALE_S without a successful "
        "/status poll — unreachable, backing off, or answering "
        "malformed/truncated JSON (replica, url, age_s, last error); "
        "its health score pins to 0 until it answers again",
    ),
    # -------------------------------------------------------------- router
    # Front-door router (ISSUE 17): the admission/failover evidence
    # trail. A request's router events reconstruct its whole fleet
    # journey — admitted under what budget, forwarded where, rerouted
    # off which dead replica — without touching any replica's log.
    "router.admit": (
        "event",
        "a request cleared fleet token-budget admission and was "
        "dispatched (request id, replica, pages charged, queue wait s, "
        "affinity hit)",
    ),
    "router.reject": (
        "event",
        "a request exhausted its retry budget or timed out in the "
        "admission queue and returned 503 — the router's only loss "
        "mode, and it is explicit, bounded, and counted",
    ),
    "router.retry": (
        "event",
        "one forward attempt failed (timeout, refused, 5xx) and the "
        "request re-dispatched after backoff (request id, attempt, "
        "failed replica, error)",
    ),
    "router.reroute": (
        "event",
        "a retry landed on a different replica than the failed one — "
        "the transparent-failover case: replica died or stalled "
        "mid-request, client still sees exactly one answer",
    ),
    "router.drain": (
        "event",
        "a replica flipped serve_draining in its /status (SIGTERM "
        "landed): no new admissions route there; its queued-but-"
        "unstarted work re-enters the pick loop",
    ),
    "router.replace": (
        "event",
        "the autoscale loop launched a prewarm_cache-seeded "
        "replacement or requested scale-up (action, replica, reason: "
        "stale | occupancy | slo_rate)",
    ),
    "router.ship": (
        "event",
        "disaggregated serving (ISSUE 19): a long prompt's KV pages "
        "were prefilled on a prefill-role replica and committed — the "
        "decode forward carries the returned kv_key (request id, "
        "prefill replica, key)",
    ),
    "router.ship_fallback": (
        "event",
        "the KV ship hop failed (no prefill capacity, dead replica "
        "mid-ship, torn commit) and the request degraded to local "
        "prefill on the decode replica — never an error, never a "
        "drop, but counted so the degradation is observable",
    ),
    "router.queue_depth": (
        "gauge",
        "requests waiting in the front door's admission queue for "
        "fleet token budget (backpressure queues here, never drops)",
    ),
    "router.budget_pages": (
        "gauge",
        "fleet token budget the admission gate sees: summed pages_free "
        "over routable replicas minus pages the router has charged to "
        "in-flight requests",
    ),
    # --------------------------------------------------------------- trace
    "trace.escalate": (
        "event",
        "tail-sampling override: a head-unsampled trace force-recorded "
        "by an SLO breach, reroute, forward error, or queue timeout "
        "(trace id, request id, first reason) — the tail is never lost "
        "to the sampler",
    ),
    "trace.flush": (
        "event",
        "one recorded trace context drained its span buffer to the "
        "writer's trace JSONL (trace id, request id, span count, "
        "writer, escalation reason if any)",
    ),
    "trace.spans": (
        "counter",
        "spans appended to this process's trace-<writer>.jsonl (one "
        "O_APPEND write per flush — torn-tail-safe like the registry)",
    ),
    "trace.dropped": (
        "counter",
        "spans discarded because no trace directory resolves "
        "(TPUFLOW_TRACE_DIR unset and telemetry off) or the append "
        "failed — tracing never raises into the serving path",
    ),
    # --------------------------------------------------------------- quant
    "quant.decision": (
        "event",
        "quantization policy verdict at model load (mode, apply, float "
        "weight MiB, measured rationale) — emitted by maybe_quantize and "
        "by a quant-armed ServeEngine, so a run's events say which "
        "numeric path its decode took",
    ),
    "quant.kernel_fallback": (
        "event",
        "a forced-pallas int8 matmul shape could not tile (K/N % 128) "
        "and fell back to the XLA int8 path — numerics identical, "
        "recorded once per shape so a bench can attribute perf to the "
        "impl that actually ran",
    ),
    # ----------------------------------------------------------------- ops
    "ops.flash_bwd_fused": (
        "event",
        "a differentiated flash-attention call traced the FUSED two-"
        "kernel backward (ISSUE 10; seq/heads/causal/blocks) — absent "
        "when TPUFLOW_FLASH_BWD=split|blockwise selected a fallback, so "
        "a run's backward provenance is auditable from the stream",
    ),
    # ---------------------------------------------------------------- dist
    "dist.mesh_generation": (
        "gauge",
        "the mesh generation this member (re-)initialized into (elastic "
        "gang: 0 at launch, bumped by every shrink/grow re-form; carries "
        "the member count and the resize reason)",
    ),
    # -------------------------------------------------------------- device
    "device.bytes_in_use": ("gauge", "sampled per-device HBM bytes in use"),
    "device.peak_bytes_in_use": ("gauge", "per-device peak HBM bytes"),
    # Device observatory (ISSUE 15): the per-program compile/memory
    # ledger, the throttled HBM gauges the StepClock/ServeEngine fences
    # feed, and the static budget check — emitted by tpuflow.obs.device,
    # read by `python -m tpuflow.obs device-summary`, the timeline
    # card's Device section, and the tpu_watch HBM segments.
    "device.program": (
        "event",
        "one compiled XLA program's ledger entry (program, compile_s, "
        "cost_analysis flops/bytes-accessed, memory_analysis argument/"
        "output/temp/generated-code bytes — absent keys where the "
        "backend can't report); the same record lands in the "
        "programs.json run artifact",
    ),
    "device.hbm_used": (
        "gauge",
        "HBM bytes in use on the busiest local device "
        "(memory_stats()['bytes_in_use'] max over devices), polled at "
        "the fences the hot loops already pay (TPUFLOW_DEVICE_POLL_S)",
    ),
    "device.hbm_peak": (
        "gauge",
        "peak HBM bytes on the busiest local device since process "
        "start (memory_stats()['peak_bytes_in_use'] max over devices)",
    ),
    "device.hbm_limit": (
        "gauge",
        "allocatable HBM bytes of the tightest local device "
        "(memory_stats()['bytes_limit'] min over devices — the device "
        "that OOMs first)",
    ),
    "device.hbm_budget": (
        "event",
        "static HBM budget verdict: resident program temp+argument "
        "bytes summed over the compiled inventory vs bytes_limit "
        "(over=True warns BEFORE an OOM; ratio keys absent off-TPU)",
    ),
    # -------------------------------------------------------------- alerts
    # Decision observatory (ISSUE 16): the run registry's append audit
    # trail and the alert engine's deduplicated lifecycle — emitted by
    # tpuflow.obs.registry / tpuflow.obs.alerts, read by the timeline
    # card's Alerts section, the /alerts endpoint, and the tpu_watch
    # ALERT lines.
    "registry.append": (
        "event",
        "one schema-versioned headline record appended to the "
        "TPUFLOW_REGISTRY_PATH run registry (path, run_id, kind, "
        "metric count) — the cross-run regression ledger's write "
        "audit",
    ),
    "alert.fired": (
        "event",
        "a declarative alert rule entered its firing condition "
        "(rule, severity, runbook anchor, message, value) — emitted "
        "ONCE per activation; the condition persisting is deduplicated",
    ),
    "alert.resolved": (
        "event",
        "an active alert's condition cleared after at least "
        "TPUFLOW_ALERT_COOLDOWN_S of activity (rule, severity, "
        "runbook, active_s) — flaps inside the cooldown never emit",
    ),
    # -------------------------------------------------------------- prof
    "prof.capture": (
        "event",
        "one anomaly-triggered bounded profiler capture committed "
        "(reason=step_time|itl|slo_ttft|slo_itl|nonfinite, trace dir, "
        "device-memory dump path, governor counters) — tpuflow.obs."
        "profcap, armed by TPUFLOW_PROF_TRIGGER",
    ),
    # -------------------------------------------------------------- health
    # Training-health observatory (ISSUE 3): per-step numerics computed
    # inside the jitted train step, emitted through the StepClock fences,
    # plus the divergence-policy events (tpuflow.obs.health).
    "health.loss": ("gauge", "per-step train loss (fenced host copy)"),
    "health.grad_norm": (
        "gauge",
        "pre-clip global gradient norm of the fenced step (spikes "
        "predict divergence; ~0 flags dead gradients)",
    ),
    "health.update_norm": (
        "gauge",
        "global norm of the applied parameter update (post-optimizer)",
    ),
    "health.param_norm": (
        "gauge",
        "global parameter norm after the step (drift/blowup evidence)",
    ),
    "health.nonfinite": (
        "counter",
        "steps whose fused on-device NaN/Inf flag fired (loss or grads)",
    ),
    "health.anomaly": (
        "event",
        "HealthMonitor detection: nonfinite streak, grad explosion, or "
        "median+MAD loss spike (kind, step, detector detail)",
    ),
    "health.rollback": (
        "event",
        "divergence auto-rollback: restored the last crc-verified "
        "checkpoint step (from_step → step, lr_scale when backed off)",
    ),
    "health.profile": (
        "event",
        "windowed jax.profiler capture committed (TPUFLOW_PROFILE="
        "start:stop): step window + trace directory",
    ),
    # ------------------------------------------------------------- goodput
    # Run observatory (ISSUE 6): goodput-so-far gauges emitted at the
    # fences StepClock already pays. The authoritative per-run ledger —
    # wall time decomposed into step/replay/compile/restore/data-wait/
    # ckpt/requeue-gap buckets — is computed by ``obs.summarize`` (and
    # ``Run.goodput()``) from the merged stream; these gauges are the
    # incremental in-run view the live export endpoint serves.
    "goodput.productive_s": (
        "gauge",
        "cumulative settled train-step seconds this process banked so "
        "far (the ledger's productive bucket)",
    ),
    "goodput.lost_s": (
        "gauge",
        "process wall seconds so far NOT spent in settled train steps "
        "(compile, restore, waits, gaps — decomposed precisely by the "
        "summarize-time goodput ledger)",
    ),
    "goodput.fraction": (
        "gauge",
        "productive fraction of this process's wall time so far "
        "(goodput-so-far; the run-level number comes from the merged "
        "ledger)",
    ),
    # ----------------------------------------------------------------- obs
    "obs.dropped": (
        "event",
        "telemetry events lost by this recorder (buffer overflow or a "
        "failed flush), surfaced once at close — never silently",
    ),
    "obs.flight": (
        "event",
        "a crash-forensics flight dump was written (reason + path): the "
        "bounded ring of recent events, env/config fingerprint, and "
        "faulting stack — referenced from the supervisor's "
        "flow.member_failed event",
    ),
    "obs.export": (
        "event",
        "the live metrics endpoint started serving /metrics (Prometheus "
        "text) + /status (JSON) on gang member 0 "
        "(TPUFLOW_OBS_HTTP_PORT); carries the bound port",
    ),
    # ------------------------------------------------------------ warnings
    "warn.flash_min_seq_malformed": (
        "event",
        "TPUFLOW_FLASH_MIN_SEQ env var was set but unparsable; the "
        "threshold fell through to the tuning file / shipped default",
    ),
}


def kind_of(name: str) -> str:
    """Registered kind of ``name``; raises KeyError for unknown names."""
    return CATALOG[name][0]


def is_registered(name: str) -> bool:
    return name in CATALOG
