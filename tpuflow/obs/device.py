"""Device observatory (ISSUE 15): the device-side twin of the goodput
ledger — what the chip compiled, what it costs, and what HBM is doing.

The observatories so far (goodput PR 6, serving PR 13, fleet PR 14)
account for host **wall time**; nothing observed the **device**. This
module closes that gap with three host-side pieces (no jitted program
gains an operand — ``compile_stats()`` is identical with everything
armed):

- **Program ledger.** :class:`ProgramLedger` records one entry per
  compiled XLA program — compile wall-s, ``cost_analysis()`` FLOPs /
  bytes-accessed, ``memory_analysis()`` argument/output/temp/
  generated-code bytes — into a ``programs.json`` run artifact (merged
  by program name across writers: warmup fences, the train compile
  fence, the AOT prewarm tool) plus a ``device.program`` event per
  entry. Backends that can't report (CPU ``memory_stats()`` is None;
  some backends raise from the analyses) degrade to **absent keys**
  with a once-per-process note — never a crash, never invented numbers
  (the Orbax artifact discipline: the ledger is the machine-readable
  handoff interface for compiled programs).

- **HBM gauges.** :func:`maybe_emit_hbm` polls ``device.memory_stats()``
  at the fences ``StepClock``/``ServeEngine`` already pay, throttled by
  ``TPUFLOW_DEVICE_POLL_S`` — ``device.hbm_used`` / ``device.hbm_peak``
  / ``device.hbm_limit`` gauges in the event stream and the live
  ``/metrics`` + ``/status`` exporter (``tpuflow_hbm_*``). Off-TPU the
  poller disables itself after the first probe; every later call is one
  module-bool check.

- **Static HBM budget check.** :meth:`ProgramLedger.budget_check` sums
  resident program temp+argument bytes against
  ``memory_stats()['bytes_limit']`` and records a ``device.hbm_budget``
  event — warning *before* an OOM, at warmup/prewarm time, not at step
  3000. The sum double-counts arguments shared between programs
  (params), which keeps the check conservative: it can only warn early.

Consumers: ``python -m tpuflow.obs device-summary <run_dir>`` (jax-free
— jax is only imported inside the polling/lowering helpers), the
timeline card's Device section, ``tpu_watch --follow/--fleet`` HBM
segments, and bench legs persisting the ledger beside their records.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Iterable

from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs

PROGRAMS_NAME = "programs.json"

# Budget-check warn threshold: resident program bytes above this
# fraction of bytes_limit are flagged. Below 1.0 on purpose — runtime
# allocations (activations in flight, collectives scratch) ride on top
# of the static program footprint, so "fits exactly" already means OOM.
BUDGET_WARN_FRAC = 0.9

_WARNED: set[str] = set()


def _warn_once(key: str, msg: str) -> None:
    """Graceful-degradation notes print once per process: an off-TPU
    backend answering None for every program must not spam one line per
    ledger entry."""
    if key not in _WARNED:
        _WARNED.add(key)
        print(f"[tpuflow] {msg}")


# --------------------------------------------- compiled-program analysis
def cost_analysis_dict(compiled_or_lowered) -> dict[str, float]:
    """``cost_analysis()`` → ``{flops, bytes_accessed}``, tolerating the
    per-version return shapes (``Compiled`` returns a list of per-module
    dicts on some backends, ``Lowered`` a plain dict) and backends that
    raise — absent keys, never a crash."""
    out: dict[str, float] = {}
    try:
        ca = compiled_or_lowered.cost_analysis()
    except Exception as e:
        _warn_once(
            "cost_analysis",
            "device ledger: cost_analysis unavailable on this backend "
            f"({e!r}); recording absent keys",
        )
        return out
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = ca.get("flops")
        if isinstance(flops, (int, float)):
            out["flops"] = float(flops)
        accessed = ca.get("bytes accessed")
        if isinstance(accessed, (int, float)):
            out["bytes_accessed"] = float(accessed)
    return out


_MEM_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def memory_analysis_dict(compiled) -> dict[str, int]:
    """``memory_analysis()`` → byte counts by role; ``None`` returns and
    raising backends degrade to absent keys with a once-per-process
    note."""
    out: dict[str, int] = {}
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        _warn_once(
            "memory_analysis",
            "device ledger: memory_analysis unavailable on this backend "
            f"({e!r}); recording absent keys",
        )
        return out
    if ma is None:
        _warn_once(
            "memory_analysis_none",
            "device ledger: memory_analysis() returned None on this "
            "backend; recording absent keys",
        )
        return out
    for attr, key in _MEM_ATTRS:
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    return out


def compiled_entry(
    name: str,
    compiled,
    *,
    compile_s: float | None = None,
    source: str | None = None,
) -> dict[str, Any]:
    """One ledger entry from an AOT-compiled program (``jit(...)
    .lower(...).compile()`` — the only object that carries BOTH
    analyses)."""
    entry: dict[str, Any] = {"name": str(name)}
    if compile_s is not None:
        entry["compile_s"] = round(float(compile_s), 4)
    if source:
        entry["source"] = source
    entry.update(cost_analysis_dict(compiled))
    entry.update(memory_analysis_dict(compiled))
    return entry


# -------------------------------------------------------- program ledger
class ProgramLedger:
    """Per-run ledger of compiled-program footprints.

    Entries merge by program NAME — the warmup fence records compile
    wall-s, the AOT path later enriches the same name with cost/memory
    analysis, and ``write()`` merges with whatever an earlier writer
    already persisted, so one ``programs.json`` accumulates the run's
    whole compiled inventory."""

    def __init__(self, source: str = "run"):
        self.source = source
        self._by_name: dict[str, dict] = {}
        self.budget: dict[str, Any] | None = None

    @property
    def programs(self) -> list[dict]:
        return list(self._by_name.values())

    def note_entry(self, entry: dict) -> dict:
        """Record (or enrich) one entry and emit its ``device.program``
        event. The event renames ``name`` → ``program`` (the recorder
        schema already uses ``name`` for the catalog name)."""
        name = str(entry.get("name", "?"))
        merged = self._by_name.setdefault(name, {"name": name})
        merged.update({k: v for k, v in entry.items() if v is not None})
        merged.setdefault("source", self.source)
        attrs = {k: v for k, v in merged.items() if k != "name"}
        _rec.event("device.program", program=name, **attrs)
        return merged

    def note_compiled(
        self, name: str, compiled, *, compile_s: float | None = None
    ) -> dict:
        return self.note_entry(
            compiled_entry(
                name, compiled, compile_s=compile_s, source=self.source
            )
        )

    # ------------------------------------------------------ budget check
    def resident_bytes(self) -> int:
        """Static residency claim of the recorded inventory: temp +
        argument bytes summed over programs. Arguments shared between
        programs (params pytrees) double-count — deliberately: the check
        is an early-warning upper bound, and a conservative bound can
        only warn early, never miss an OOM it could have seen."""
        total = 0
        for e in self._by_name.values():
            total += int(e.get("temp_bytes", 0)) + int(
                e.get("argument_bytes", 0)
            )
        return total

    def budget_check(
        self, bytes_limit: int | None = None, *, devices=None
    ) -> dict[str, Any]:
        """Static HBM budget verdict, recorded as a ``device.hbm_budget``
        event. ``bytes_limit`` defaults from ``memory_stats()`` (absent
        off-TPU → the verdict carries resident bytes only, no ratio —
        keys absent, never invented)."""
        if bytes_limit is None:
            snap = hbm_snapshot(devices)
            if snap is not None:
                bytes_limit = snap.get("limit")
        resident = self.resident_bytes()
        verdict: dict[str, Any] = {
            "resident_bytes": resident,
            "programs": len(self._by_name),
        }
        if bytes_limit:
            frac = resident / float(bytes_limit)
            verdict["bytes_limit"] = int(bytes_limit)
            verdict["resident_frac"] = round(frac, 4)
            verdict["over"] = frac > BUDGET_WARN_FRAC
            if verdict["over"]:
                print(
                    "[tpuflow] device ledger: static program residency "
                    f"{resident / 2**30:.2f} GiB is {100.0 * frac:.0f}% "
                    f"of the {bytes_limit / 2**30:.2f} GiB HBM limit — "
                    "expect allocation pressure or OOM "
                    "(README: Device observatory runbook)"
                )
        _rec.event("device.hbm_budget", **verdict)
        self.budget = verdict
        return verdict

    # ------------------------------------------------------------- write
    def write(self, path: str | None = None) -> str | None:
        """Persist (merge-by-name with any existing file) the ledger as
        ``programs.json``. Default location: beside the recorder's event
        fragments (``<obs_dir>/programs.json``); with telemetry disabled
        and no explicit path, a no-op returning None. Atomic tmp+rename
        so a concurrent reader never sees a torn artifact."""
        if path is None:
            rec = _rec.recorder()
            if rec is None:
                return None
            path = os.path.join(rec.directory, PROGRAMS_NAME)
        existing: dict[str, dict] = {}
        budget = self.budget
        try:
            with open(path) as f:
                prior = json.load(f)
            for e in prior.get("programs", []):
                if isinstance(e, dict) and e.get("name"):
                    existing[str(e["name"])] = e
            if budget is None and isinstance(prior.get("budget"), dict):
                budget = prior["budget"]
        except (OSError, ValueError):
            pass
        for name, e in self._by_name.items():
            merged = existing.setdefault(name, {"name": name})
            merged.update({k: v for k, v in e.items() if v is not None})
        record: dict[str, Any] = {
            "written_ts": time.time(),
            "source": self.source,
            "programs": sorted(
                existing.values(), key=lambda e: e.get("name", "")
            ),
        }
        if budget is not None:
            record["budget"] = budget
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            # The ledger is evidence, never a failure mode.
            print(f"[tpuflow] device ledger write failed (ignored): {e}")
            return None
        return path


def note_jit_program(
    name: str,
    jit_fn,
    args: tuple,
    *,
    compile_s: float | None = None,
    source: str = "train",
) -> dict | None:
    """Record an already-jitted function at its compile fence.

    Re-lowering an executed jit fn is TRACE-only (no XLA backend
    compile), so this collects ``Lowered.cost_analysis()`` cheaply;
    ``memory_analysis`` needs the compiled executable and is only
    recorded on the AOT paths (``ServeEngine.aot_lower`` /
    ``tools/prewarm_cache.py``) — absent keys here, by design. Gated on
    telemetry + ``TPUFLOW_DEVICE_LEDGER``; never raises into the loop."""
    if not _rec.enabled():
        return None
    if not knobs.get_bool("TPUFLOW_DEVICE_LEDGER"):
        return None
    entry: dict[str, Any] = {"name": str(name), "source": source}
    if compile_s is not None:
        entry["compile_s"] = round(float(compile_s), 4)
    try:
        lowered = jit_fn.lower(*args)
        entry.update(cost_analysis_dict(lowered))
    except Exception as e:
        _warn_once(
            f"lower:{name}",
            f"device ledger: re-lowering {name!r} for cost analysis "
            f"failed ({e!r}); recording compile time only",
        )
    ledger = ProgramLedger(source=source)
    ledger.note_entry(entry)
    ledger.write()
    return entry


# ------------------------------------------------------------ HBM gauges
def hbm_snapshot(devices=None) -> dict[str, Any] | None:
    """One ``memory_stats()`` sweep over ``devices`` (default
    ``jax.local_devices()``; tests inject fakes). Returns ``{devices,
    used, peak, limit}`` — ``used``/``peak`` are the max over devices
    and ``limit`` the min (the binding device is the one that OOMs
    first), each key present only when at least one device reported it.
    ``None`` when no device answers (CPU backends return None) — absent
    keys, never invented."""
    if devices is None:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return None
    used = peak = limit = None
    n = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        n += 1
        u = stats.get("bytes_in_use")
        if isinstance(u, (int, float)):
            used = int(u) if used is None else max(used, int(u))
        p = stats.get("peak_bytes_in_use")
        if isinstance(p, (int, float)):
            peak = int(p) if peak is None else max(peak, int(p))
        lim = stats.get("bytes_limit")
        if isinstance(lim, (int, float)):
            limit = int(lim) if limit is None else min(limit, int(lim))
    if n == 0:
        return None
    out: dict[str, Any] = {"devices": n}
    if used is not None:
        out["used"] = used
    if peak is not None:
        out["peak"] = peak
    if limit is not None:
        out["limit"] = limit
    return out


def emit_hbm(snap: dict) -> None:
    """Record one HBM snapshot as gauges + the live process-ledger feed
    (the /metrics ``tpuflow_hbm_*`` rows)."""
    used = snap.get("used")
    peak = snap.get("peak")
    limit = snap.get("limit")
    if used is not None:
        _rec.gauge("device.hbm_used", used)
    if peak is not None:
        _rec.gauge("device.hbm_peak", peak)
    if limit is not None:
        _rec.gauge("device.hbm_limit", limit)
    from tpuflow.obs import goodput as _goodput

    _goodput.live().note_device_hbm(used, peak, limit)


# Poller state: one monotonic compare when the interval hasn't elapsed,
# one bool check forever after the first probe on a backend without
# memory_stats — the fences that call this are the hot loop's.
_POLL_NEXT = 0.0
_POLL_OFF = False


def maybe_emit_hbm(force: bool = False, devices=None) -> dict | None:
    """Throttled HBM poll for the StepClock / ServeEngine fences
    (``TPUFLOW_DEVICE_POLL_S``; 0 disables). Self-disables after the
    first probe on a backend where ``memory_stats()`` is unavailable."""
    global _POLL_NEXT, _POLL_OFF
    if _POLL_OFF and not force:
        return None
    now = time.monotonic()
    if not force and now < _POLL_NEXT:
        return None
    interval = knobs.get_float_lenient("TPUFLOW_DEVICE_POLL_S")
    if interval <= 0 and not force:
        _POLL_OFF = True
        return None
    _POLL_NEXT = now + max(float(interval), 0.0)
    snap = hbm_snapshot(devices)
    if snap is None:
        _POLL_OFF = True
        _warn_once(
            "hbm_off",
            "device observatory: memory_stats() unavailable on this "
            "backend; HBM gauges disabled (keys absent, never invented)",
        )
        return None
    _POLL_OFF = False
    emit_hbm(snap)
    return snap


def _reset_for_tests() -> None:
    global _POLL_NEXT, _POLL_OFF
    _POLL_NEXT = 0.0
    _POLL_OFF = False
    _WARNED.clear()


# ------------------------------------------------------ jax-free reading
def load_programs(run_dir: str) -> dict | None:
    """The run's ``programs.json`` (``<run_dir>/obs/`` or the run root),
    or None. Pure file reading — safe from a login shell mid-run."""
    from tpuflow.obs.timeline import OBS_SUBDIR

    for candidate in (
        os.path.join(run_dir, OBS_SUBDIR, PROGRAMS_NAME),
        os.path.join(run_dir, PROGRAMS_NAME),
    ):
        try:
            with open(candidate) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and isinstance(rec.get("programs"), list):
            rec["path"] = candidate
            return rec
    return None


def device_summary(run_dir: str) -> dict[str, Any]:
    """Fold the run's device evidence — ``programs.json``, the last
    ``device.hbm_*`` gauges, budget verdicts, and ``prof.capture``
    events — into one dict (the ``device-summary`` CLI's payload).
    jax-free: file reads only."""
    from tpuflow.obs.timeline import load_run_events

    out: dict[str, Any] = {}
    ledger = load_programs(run_dir)
    if ledger:
        out["programs_path"] = ledger.get("path")
        out["programs"] = ledger["programs"]
        if isinstance(ledger.get("budget"), dict):
            out["budget"] = ledger["budget"]
    hbm: dict[str, float] = {}
    captures: list[dict] = []
    for ev in load_run_events(run_dir):
        kind, name = ev.get("kind"), ev.get("name", "")
        if kind == "gauge" and name in (
            "device.hbm_used", "device.hbm_peak", "device.hbm_limit"
        ):
            try:
                key = name[len("device."):]
                v = float(ev.get("value", 0.0))
                hbm[key] = v
                if key != "hbm_limit":
                    hbm[f"{key}_max"] = max(hbm.get(f"{key}_max", 0.0), v)
            except (TypeError, ValueError):
                pass
        elif kind == "event" and name == "prof.capture":
            captures.append({
                k: v for k, v in ev.items()
                if k not in ("kind", "name", "pid")
            })
        elif kind == "event" and name == "device.hbm_budget":
            out.setdefault("budget", {
                k: v for k, v in ev.items()
                if k not in ("kind", "name", "ts", "proc", "pid", "launch")
            })
    if hbm:
        out["hbm"] = hbm
    if captures:
        out["captures"] = captures
    return out


def summarize_entry(e: dict) -> str:
    """One human table line for a programs.json entry."""

    def _fmt_bytes(v):
        return "-" if v is None else f"{v / 2**20:9.2f}"

    flops = e.get("flops")
    return (
        f"  {e.get('name', '?'):<16} "
        f"{e.get('compile_s', '-')!s:>9}  "
        f"{'-' if flops is None else f'{flops:.3g}':>10}  "
        f"{_fmt_bytes(e.get('argument_bytes')):>9}  "
        f"{_fmt_bytes(e.get('output_bytes')):>9}  "
        f"{_fmt_bytes(e.get('temp_bytes')):>9}"
    )
