"""Live export: /metrics (Prometheus), /status (JSON), /alerts mid-run.

Opt-in via ``TPUFLOW_OBS_HTTP_PORT``: gang member 0 (or the training
process itself, outside a gang) starts one daemon-threaded HTTP server
serving the live goodput ledger (``tpuflow.obs.goodput.live()``) — step
rate, tokens/s, rolling MFU from the model's FLOP estimate,
goodput-so-far, and the last health gauges. Polling a file was never an
option mid-run: the recorder buffers off the hot path and the merged
``events.jsonl`` only exists after the run; this endpoint is how
``tools/tpu_watch.py --follow`` (and a real Prometheus scraper) watch a
run while it trains.

Zero cost when off: without the env knob ``maybe_start_from_env`` is a
single dict lookup; with it, the server runs entirely on its own daemon
thread and reads only in-memory counters — nothing lands on the step
path.

Knobs: ``TPUFLOW_OBS_HTTP_PORT`` (0 = ephemeral, the chosen port is
printed and recorded as an ``obs.export`` event), ``TPUFLOW_OBS_HTTP_HOST``
(default 127.0.0.1 — bind 0.0.0.0 explicitly to let a remote scraper in).
"""

from __future__ import annotations

import json
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpuflow.obs import fleet as _fleet
from tpuflow.obs import goodput as _goodput
from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs

_SERVER: "MetricsServer | None" = None

# (prometheus metric, snapshot key, prometheus type) — one stable,
# documented mapping so dashboards don't chase snapshot-dict drift.
_PROM_SPEC = (
    ("tpuflow_uptime_seconds", "uptime_s", "gauge"),
    ("tpuflow_steps_total", "steps", "counter"),
    ("tpuflow_reports_total", "reports", "counter"),
    ("tpuflow_step", "step", "gauge"),
    ("tpuflow_tokens_total", "tokens", "counter"),
    ("tpuflow_step_rate", "step_rate", "gauge"),
    ("tpuflow_tokens_per_s", "tokens_per_s", "gauge"),
    ("tpuflow_mfu", "mfu", "gauge"),
    ("tpuflow_goodput_fraction", "goodput_fraction", "gauge"),
    ("tpuflow_productive_seconds_total", "productive_s", "counter"),
    ("tpuflow_compile_seconds_total", "compile_s", "counter"),
    ("tpuflow_loss", "loss", "gauge"),
    ("tpuflow_grad_norm", "grad_norm", "gauge"),
    ("tpuflow_nonfinite_steps_total", "nonfinite_steps", "counter"),
    # Device observatory (ISSUE 15): HBM residency of the busiest local
    # device (limit = the tightest device's); keys only present when
    # the backend reports memory_stats — absent off-TPU, never zeroed.
    ("tpuflow_hbm_used_bytes", "hbm_used_bytes", "gauge"),
    ("tpuflow_hbm_peak_bytes", "hbm_peak_bytes", "gauge"),
    ("tpuflow_hbm_limit_bytes", "hbm_limit_bytes", "gauge"),
    ("tpuflow_hbm_used_frac", "hbm_used_frac", "gauge"),
    ("tpuflow_hbm_peak_frac", "hbm_peak_frac", "gauge"),
    # Serving engine (tpuflow.infer.serve): keys only present when an
    # engine feeds this process's ledger, omitted on training runs.
    ("tpuflow_serve_requests_total", "serve_requests", "counter"),
    ("tpuflow_serve_tokens_total", "serve_tokens", "counter"),
    ("tpuflow_serve_queue_depth", "serve_queue_depth", "gauge"),
    ("tpuflow_serve_slot_occupancy", "serve_slot_occupancy", "gauge"),
    ("tpuflow_serve_tokens_per_s", "serve_tokens_per_s", "gauge"),
    ("tpuflow_serve_ttft_p50_seconds", "serve_ttft_p50_s", "gauge"),
    ("tpuflow_serve_ttft_p99_seconds", "serve_ttft_p99_s", "gauge"),
    # Paged KV (ISSUE 11): pool headroom, shared-prefix reuse, and
    # per-request speculative acceptance; keys only present on paged /
    # spec-armed engines.
    ("tpuflow_serve_pages_free", "serve_pages_free", "gauge"),
    ("tpuflow_serve_prefix_hit_rate", "serve_prefix_hit_rate", "gauge"),
    ("tpuflow_serve_spec_accept_rate", "serve_spec_accept_rate", "gauge"),
    # Tiered prefix cache (ISSUE 19): pages parked in the host-DRAM /
    # node-local-disk tiers; keys only present when a tier is armed
    # (TPUFLOW_KV_HOST_MB / TPUFLOW_KV_DISK_DIR).
    ("tpuflow_serve_pages_host", "serve_pages_host", "gauge"),
    ("tpuflow_serve_pages_disk", "serve_pages_disk", "gauge"),
    # Serving observatory (ISSUE 13): engine-time ledger fractions, ITL
    # percentiles, and declared-SLO accounting; keys only present while
    # an engine feeds this process's ledger.
    ("tpuflow_serve_ttft_p95_seconds", "serve_ttft_p95_s", "gauge"),
    ("tpuflow_serve_itl_p50_seconds", "serve_itl_p50_s", "gauge"),
    ("tpuflow_serve_itl_p95_seconds", "serve_itl_p95_s", "gauge"),
    ("tpuflow_serve_itl_p99_seconds", "serve_itl_p99_s", "gauge"),
    ("tpuflow_serve_idle_fraction", "serve_idle_fraction", "gauge"),
    ("tpuflow_serve_decode_fraction", "serve_decode_fraction", "gauge"),
    ("tpuflow_serve_prefill_fraction", "serve_prefill_fraction", "gauge"),
    ("tpuflow_serve_decode_utilization", "serve_decode_utilization",
     "gauge"),
    ("tpuflow_serve_masked_row_waste", "serve_masked_row_waste", "gauge"),
    ("tpuflow_serve_slo_violations_total", "serve_slo_violations",
     "counter"),
)


# Cumulative TTFT/ITL histograms (ISSUE 14): Prometheus histogram
# convention — per-bucket counts cumulated into le-labeled counts plus
# _sum/_count. Unlike the pre-aggregated percentile GAUGES above (which
# stay, for single-replica dashboards), bucket counts MERGE across
# replicas by summation, which is what makes fleet-exact percentiles
# possible (tpuflow.obs.fleet merges them; the fleet p99 from summed
# buckets is bit-equal to bucketing the pooled raw observations).
_PROM_HISTS = (
    ("tpuflow_serve_ttft_seconds", "serve_ttft_hist"),
    ("tpuflow_serve_itl_seconds", "serve_itl_hist"),
)


def prometheus_text(snapshot: dict) -> str:
    """Render a ledger snapshot as Prometheus text exposition (0.0.4).
    Keys absent from the snapshot (MFU off-TPU, rates before the second
    fence) are omitted rather than invented."""
    lines = []
    for metric, key, ptype in _PROM_SPEC:
        v = snapshot.get(key)
        if not isinstance(v, (int, float)):
            continue
        lines.append(f"# TYPE {metric} {ptype}")
        lines.append(f"{metric} {float(v):.10g}")
    for metric, key in _PROM_HISTS:
        h = snapshot.get(key)
        if not isinstance(h, dict) or not h.get("count"):
            continue
        try:
            edges = list(h["edges"])
            counts = [int(c) for c in h["counts"]]
        except (TypeError, KeyError, ValueError):
            continue
        if len(counts) != len(edges) + 1:
            continue
        lines.append(f"# TYPE {metric} histogram")
        acc = 0
        for edge, c in zip(edges, counts):
            acc += c
            lines.append(f'{metric}_bucket{{le="{float(edge):.10g}"}} {acc}')
        acc += counts[-1]
        lines.append(f'{metric}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{metric}_sum {float(h.get('sum', 0.0)):.10g}")
        lines.append(f"{metric}_count {int(h['count'])}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def _snapshot(self) -> dict:
        """This server's snapshot source: the per-server override when
        set (tests run several in-process replicas, each over its own
        ledger), else the process's live goodput ledger."""
        fn = getattr(self.server, "_tpuflow_snapshot", None)
        return fn() if fn is not None else _goodput.live().snapshot()

    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            route = self.path.split("?", 1)[0]
            if route == "/metrics":
                body = prometheus_text(self._snapshot()).encode()
                ctype = "text/plain; version=0.0.4"
            elif route in ("/status", "/"):
                snap = self._snapshot()
                snap["pid"] = os.getpid()
                # Replica identity (ISSUE 14): fleet aggregation needs
                # to know WHO answered — replica id, launch attempt,
                # elastic mesh generation when known.
                snap.setdefault("replica", _fleet.replica_identity())
                body = (json.dumps(snap) + "\n").encode()
                ctype = "application/json"
            elif route == "/alerts":
                # Alert engine (ISSUE 16): every scrape evaluates the
                # rules against a fresh snapshot, so firing/resolving
                # needs no separate poller thread — the scraper IS the
                # sweep. Serialized: handler threads share one engine.
                eng = getattr(self.server, "_tpuflow_alerts", None)
                if eng is None:
                    self.send_error(404)
                    return
                with self.server._tpuflow_alerts_lock:
                    eng.observe(status=self._snapshot())
                    payload = {
                        "active": eng.active(),
                        "rules": eng.describe(),
                    }
                body = (json.dumps(payload) + "\n").encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response

    def log_message(self, fmt, *args):
        pass  # scrapes must not spam the member's step log


class MetricsServer:
    """One daemon-threaded HTTP server over the live ledger (or, with
    ``snapshot_fn``, over any snapshot source — the fleet tests run
    several in-process replicas this way)."""

    def __init__(
        self, port: int = 0, host: str = "127.0.0.1", snapshot_fn=None,
        alert_engine=None,
    ):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd._tpuflow_snapshot = snapshot_fn
        self._httpd._tpuflow_alerts = alert_engine
        self._httpd._tpuflow_alerts_lock = threading.Lock()
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            daemon=True,
            name="tpuflow-obs-export",
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2)


def maybe_start_from_env(proc: int | None = None) -> MetricsServer | None:
    """Start the export server when ``TPUFLOW_OBS_HTTP_PORT`` is set and
    this is gang member 0 (``proc`` defaults from TPUFLOW_OBS_PROC /
    TPUFLOW_PROCESS_ID; a non-gang training process is its own member 0).
    Idempotent per process — the train legs and the gang bootstrap both
    call it; the first caller wins, later calls return the same server.
    A bind failure disables export with a printed warning, never the run.
    """
    global _SERVER
    raw = knobs.raw("TPUFLOW_OBS_HTTP_PORT")
    if not raw:
        return None
    if _SERVER is not None:
        return _SERVER
    try:
        port = int(raw)
    except ValueError:
        print(
            f"[tpuflow] malformed TPUFLOW_OBS_HTTP_PORT={raw!r} "
            "(want an integer); live export disabled"
        )
        return None
    if proc is None:
        try:
            proc = int(
                knobs.raw("TPUFLOW_OBS_PROC")
                or knobs.raw("TPUFLOW_PROCESS_ID")
                or 0
            )
        except ValueError:
            proc = 0
    if proc != 0:
        return None  # one endpoint per gang: member 0 owns it
    host = knobs.raw("TPUFLOW_OBS_HTTP_HOST", "127.0.0.1")
    # Alert engine (ISSUE 16): the live endpoint always carries
    # /alerts — the process singleton engine, evaluated per scrape.
    from tpuflow.obs import alerts as _alerts

    try:
        _SERVER = MetricsServer(
            port, host=host, alert_engine=_alerts.engine()
        )
    except OSError as e:
        print(
            f"[tpuflow] obs export failed to bind {host}:{port} "
            f"({e}); live export disabled"
        )
        return None
    _rec.event("obs.export", port=_SERVER.port)
    # Fleet registration (ISSUE 14): the moment /status answers, stamp
    # this replica into the registration dir (if configured) so a fleet
    # observatory discovers it without a static URL list. A wildcard
    # bind advertises the host's name — 0.0.0.0 is not pollable.
    reg_url = _SERVER.url
    if host == "0.0.0.0":  # noqa: S104 (operator opted in via knob)
        reg_url = f"http://{socket.gethostname()}:{_SERVER.port}"
    _fleet.maybe_register(reg_url)
    print(
        f"[tpuflow] obs export serving /metrics + /status on {_SERVER.url}"
    )
    return _SERVER


def stop() -> None:
    """Tear the process's export server down (tests; the daemon thread
    otherwise dies with the process)."""
    global _SERVER
    if _SERVER is not None:
        _SERVER.close()
        _SERVER = None
