"""Fleet observatory (ISSUE 14): discover N serving replicas, poll their
``/status`` endpoints, and aggregate one fleet snapshot — host-side and
jax-free, safe from a login shell against a live fleet.

ROADMAP item 2's router must load-balance "using the ``tpuflow_serve_*``
gauges each replica already exports" — but until now nothing could
observe more than one replica at once, and the exported TTFT/ITL
percentiles were pre-aggregated gauges that are mathematically
unmergeable across replicas (the mean of N p99s is not the fleet p99;
neither is the max). This module is the layer above one replica's
observatory, in four pieces:

- **Mergeable histograms.** :class:`MergeableHistogram` holds raw
  TTFT/ITL observations as fixed-edge bucket counts (Prometheus
  histogram convention: per-bucket counts cumulated into ``le`` counts
  at render time). Merging across replicas is then an integer SUM —
  the merged counts are bit-equal to the counts of the pooled raw
  observations, by construction — and :func:`hist_pctl` reads fleet
  percentiles off the merged counts (exact to one bucket width of the
  pooled nearest-rank value). ``tpuflow.obs.export`` emits these beside
  the existing gauges; the gauges stay for single-replica dashboards.

- **Discovery.** :func:`discover_replicas` resolves the fleet from (in
  priority order) an explicit target, the ``TPUFLOW_FLEET_REPLICAS``
  comma URL list, or the ``TPUFLOW_FLEET_REGISTRATION_DIR`` file-based
  registry each exporting replica stamps at export start
  (:func:`maybe_register`, called from the export bootstrap
  ``serve_forever`` already routes through). A URL whose hostname
  resolves to multiple A records — the k8s headless Service
  ``flow.deploy.serving_headless_service`` creates — expands into one
  replica per pod IP, so one DNS name names the whole fleet.

- **Polling.** :class:`FleetObservatory` polls every replica's
  ``/status`` with a per-replica timeout, exponential backoff on
  consecutive failures, and staleness marking
  (``TPUFLOW_FLEET_STALE_S``): a replica that stops answering — or
  answers with malformed/truncated JSON, a snapshot read mid-write —
  is marked stale, never crashes the watcher.

- **Aggregation.** :meth:`FleetObservatory.poll` folds the fresh
  replicas into one fleet snapshot: summed QPS / queue depth /
  pages_free / tokens/s, occupancy-weighted decode utilization from
  the engine-time ledger fractions, fleet-exact TTFT/ITL p50/p95/p99
  from the merged histograms, SLO violation rates split by traffic
  group, and a per-replica health score (stale, SLO-violating,
  queue-growing, nonfinite) — the future router's admission signal.
  Snapshots optionally append to a JSONL file for post-hoc analysis
  (``TPUFLOW_FLEET_SNAPSHOT_PATH``).

Consumers: ``python -m tpuflow.obs fleet-summary`` (CLI),
``tools/tpu_watch.py --fleet`` (one line per replica + a fleet headline
line), and the timeline card's Fleet section.
"""

from __future__ import annotations

import bisect
import json
import os
import socket
import threading
import time
from typing import Any, Callable, Iterable

from tpuflow.obs import recorder as _rec
from tpuflow.obs.serve_ledger import pctl  # the shared nearest-rank math
from tpuflow.utils import knobs

# Default TTFT/ITL bucket upper edges (seconds): a Prometheus-style
# 1ms → 10s ladder wide enough for both sub-ms ITLs and multi-second
# cold TTFTs. Override with TPUFLOW_FLEET_HIST_BUCKETS (comma seconds,
# strictly increasing) — every replica of a fleet must agree on the
# edges or its histogram cannot merge (mismatches are flagged, never
# summed).
DEFAULT_HIST_EDGES: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_EDGES_WARNED = False


def resolve_hist_edges() -> tuple[float, ...]:
    """Histogram bucket edges from ``TPUFLOW_FLEET_HIST_BUCKETS``
    (comma floats, strictly increasing, positive) — malformed values
    warn once per process and fall back to the default ladder (a typo'd
    knob must not kill a server at start)."""
    global _EDGES_WARNED
    raw = knobs.raw("TPUFLOW_FLEET_HIST_BUCKETS")
    if not raw:
        return DEFAULT_HIST_EDGES
    try:
        edges = tuple(float(x) for x in raw.split(",") if x.strip())
        if not edges or any(e <= 0 for e in edges) or any(
            b <= a for a, b in zip(edges, edges[1:])
        ):
            raise ValueError(raw)
        return edges
    except ValueError:
        if not _EDGES_WARNED:
            _EDGES_WARNED = True
            print(
                f"[tpuflow] malformed TPUFLOW_FLEET_HIST_BUCKETS={raw!r} "
                "(want strictly increasing positive comma floats); using "
                "the default ladder"
            )
        return DEFAULT_HIST_EDGES


class MergeableHistogram:
    """Fixed-edge latency histogram whose cross-replica merge is a sum.

    ``counts[i]`` holds observations in ``(edges[i-1], edges[i]]``
    (first bucket: ``[0, edges[0]]``); ``counts[-1]`` is the overflow
    (> last edge). Cumulative-``le`` rendering happens at export time —
    internal counts stay per-bucket so merges and tests are plain
    integer sums."""

    __slots__ = ("edges", "counts", "count", "sum", "exemplars")

    def __init__(self, edges: Iterable[float] | None = None):
        self.edges: tuple[float, ...] = tuple(
            DEFAULT_HIST_EDGES if edges is None else edges
        )
        self.counts: list[int] = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        # Prometheus-style exemplars: each bucket remembers the LAST
        # trace id observed into it, so a fleet percentile resolves to
        # a concrete `obs trace` timeline. None entries cost nothing
        # and to_dict omits the key entirely until one is set.
        self.exemplars: list[str | None] = [None] * (len(self.edges) + 1)

    def observe(self, v: float, exemplar: str | None = None) -> None:
        i = bisect.bisect_left(self.edges, float(v))
        self.counts[i] += 1
        self.count += 1
        self.sum += float(v)
        if exemplar is not None:
            self.exemplars[i] = str(exemplar)

    def to_dict(self) -> dict[str, Any]:
        out = {
            "edges": list(self.edges),
            "counts": list(self.counts),
            "count": self.count,
            "sum": round(self.sum, 9),
        }
        if any(e is not None for e in self.exemplars):
            out["exemplars"] = list(self.exemplars)
        return out

    def cumulative(self) -> list[int]:
        """Prometheus ``le`` counts: cumulative per-bucket counts, the
        last entry (``le="+Inf"``) equal to ``count``."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


def merge_hists(hists: Iterable[dict]) -> dict[str, Any] | None:
    """Sum histogram dicts (the ``to_dict`` shape) sharing one edge
    ladder. Dicts with different edges are SKIPPED and reported under
    ``"skipped"`` — summing across mismatched edges would silently
    corrupt the fleet percentiles, the exact failure gauges have.
    Returns None when nothing merges."""
    merged: dict[str, Any] | None = None
    skipped = 0
    for h in hists:
        try:
            edges = list(h["edges"])
            counts = [int(c) for c in h["counts"]]
            if len(counts) != len(edges) + 1:
                raise ValueError("count/edge shape")
        except (TypeError, KeyError, ValueError):
            skipped += 1
            continue
        ex = h.get("exemplars")
        if not (isinstance(ex, list) and len(ex) == len(counts)):
            ex = None  # absent/malformed exemplars degrade, never skip
        if merged is None:
            merged = {
                "edges": edges,
                "counts": counts,
                "count": int(h.get("count", sum(counts))),
                "sum": float(h.get("sum", 0.0)),
            }
            if ex is not None:
                merged["exemplars"] = list(ex)
        elif edges == merged["edges"]:
            merged["counts"] = [
                a + b for a, b in zip(merged["counts"], counts)
            ]
            merged["count"] += int(h.get("count", sum(counts)))
            merged["sum"] += float(h.get("sum", 0.0))
            if ex is not None:
                prev = merged.get("exemplars") or [None] * len(counts)
                merged["exemplars"] = [
                    b if b is not None else a for a, b in zip(prev, ex)
                ]
        else:
            skipped += 1
    if merged is not None and skipped:
        merged["skipped"] = skipped
    return merged


def hist_pctl(edges, counts, q: float) -> float | None:
    """Nearest-rank percentile over histogram counts: the upper edge of
    the bucket holding the rank-``q`` observation (the same rank index
    as the shared raw-observation :func:`pctl`, so the histogram answer
    is within one bucket width of the pooled raw answer — and two
    fleets with bit-equal counts report bit-equal percentiles).
    Overflow-bucket ranks return ``inf`` (the edges under-span the
    data); empty counts return None."""
    n = sum(counts)
    if n <= 0:
        return None
    rank = min(n - 1, int(q * (n - 1) + 0.5))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc > rank:
            return float(edges[i]) if i < len(edges) else float("inf")
    return float("inf")


def hist_percentiles(h: dict | None) -> dict[str, float] | None:
    """{count, p50, p95, p99} from a histogram dict — the fleet twin of
    ``serve_ledger.percentiles`` (which works on raw observations)."""
    if not h or not h.get("count"):
        return None
    edges, counts = h["edges"], h["counts"]
    return {
        "count": int(h["count"]),
        "p50": hist_pctl(edges, counts, 0.50),
        "p95": hist_pctl(edges, counts, 0.95),
        "p99": hist_pctl(edges, counts, 0.99),
    }


def hist_exemplar(h: dict | None, q: float) -> str | None:
    """The trace-id exemplar for the bucket holding the rank-``q``
    observation (same rank walk as :func:`hist_pctl`), so "fleet p99
    TTFT regressed" resolves to a concrete ``obs trace`` timeline.
    None when the histogram is empty, carries no exemplars, or the
    target bucket never recorded one."""
    if not h or not h.get("count"):
        return None
    ex = h.get("exemplars")
    counts = h.get("counts") or []
    if not (isinstance(ex, list) and len(ex) == len(counts)):
        return None
    n = sum(counts)
    if n <= 0:
        return None
    rank = min(n - 1, int(q * (n - 1) + 0.5))
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc > rank:
            return ex[i] if isinstance(ex[i], str) else None
    return None


# ------------------------------------------------------ replica identity
def replica_identity() -> dict[str, Any]:
    """This process's replica identity, stamped into ``/status`` and the
    registration file: replica id (``TPUFLOW_FLEET_REPLICA_ID`` — the
    deploy manifest sets it from the pod name — else host+pid), launch
    attempt, and the elastic mesh generation when this process is a
    gang member. The membership module is consulted only if ALREADY
    imported — a jax-free serving/export process must not pull the
    distributed runtime in for an id stamp."""
    rid = knobs.raw("TPUFLOW_FLEET_REPLICA_ID")
    if not rid:
        rid = f"{socket.gethostname()}-{os.getpid()}"
    ident: dict[str, Any] = {"id": rid}
    try:
        ident["attempt"] = int(knobs.raw("TPUFLOW_ATTEMPT", "0") or 0)
    except ValueError:
        ident["attempt"] = 0
    import sys

    mm = sys.modules.get("tpuflow.dist.membership")
    if mm is not None:
        try:
            ident["mesh_generation"] = int(mm.current_generation())
        except Exception:
            pass
    return ident


# ----------------------------------------------------------- registration
def registration_path(directory: str, replica_id: str) -> str:
    safe = "".join(
        c if c.isalnum() or c in "-_." else "_" for c in replica_id
    )
    return os.path.join(directory, f"replica-{safe}.json")


def register_replica(
    directory: str,
    url: str,
    *,
    identity: dict | None = None,
) -> str:
    """Stamp one replica's registration file (atomic tmp+rename so a
    concurrent fleet poll never reads a torn record — and if it does
    anyway, the poller's malformed-JSON path marks it stale rather than
    crashing). Returns the path written."""
    ident = dict(identity or replica_identity())
    path = registration_path(directory, str(ident.get("id", "replica")))
    os.makedirs(directory, exist_ok=True)
    record = {
        "url": url,
        "replica": ident,
        "pid": os.getpid(),
        "registered_ts": time.time(),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, path)
    _rec.event(
        "fleet.register", url=url, replica=ident.get("id"), path=path
    )
    return path


def maybe_register(url: str) -> str | None:
    """Register into ``TPUFLOW_FLEET_REGISTRATION_DIR`` when set (the
    export bootstrap calls this the moment /status starts answering);
    a write failure warns and returns None — registration must never
    take a serving process down."""
    directory = knobs.raw("TPUFLOW_FLEET_REGISTRATION_DIR")
    if not directory:
        return None
    try:
        return register_replica(directory, url)
    except OSError as e:
        print(f"[tpuflow] fleet registration failed ({e}); skipping")
        return None


def read_registrations(directory: str) -> list[dict]:
    """Every parseable registration record under ``directory`` (sorted
    by replica id). Torn/mid-write files are skipped — the replica they
    describe will register again or age into staleness."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("replica-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and isinstance(rec.get("url"), str):
            out.append(rec)
    return out


# -------------------------------------------------------------- discovery
def _expand_dns(url: str) -> list[str]:
    """Expand a URL whose hostname resolves to multiple A records into
    one URL per address — the k8s headless-Service discovery mode (one
    DNS name → every pod IP). Literal IPs, localhost, and unresolvable
    names pass through unchanged."""
    try:
        from urllib.parse import urlsplit, urlunsplit

        parts = urlsplit(url)
        host = parts.hostname
        if not host or host == "localhost":
            return [url]
        try:
            socket.inet_aton(host)
            return [url]  # already a literal IPv4
        except OSError:
            pass
        infos = socket.getaddrinfo(
            host, parts.port or 80, socket.AF_INET, socket.SOCK_STREAM
        )
        addrs = sorted({i[4][0] for i in infos})
        if len(addrs) <= 1:
            return [url]
        port = f":{parts.port}" if parts.port else ""
        return [
            urlunsplit(
                (parts.scheme, f"{a}{port}", parts.path, parts.query, "")
            )
            for a in addrs
        ]
    except OSError:
        return [url]


def _normalize_url(u: str) -> str:
    u = u.strip().rstrip("/")
    if u and "://" not in u:
        u = f"http://{u}"
    return u


def discover_replicas(
    target: str | None = None,
) -> list[tuple[str, str | None]]:
    """Resolve the fleet into ``(url, replica_id_or_None)`` pairs.

    ``target`` (CLI arg) may be a registration directory or a comma URL
    list; with no target, ``TPUFLOW_FLEET_REPLICAS`` wins over
    ``TPUFLOW_FLEET_REGISTRATION_DIR``. Hostnames with multiple A
    records (headless Service) expand into one replica per address."""
    if target:
        if os.path.isdir(target):
            return [
                (
                    _normalize_url(r["url"]),
                    (r.get("replica") or {}).get("id"),
                )
                for r in read_registrations(target)
            ]
        urls = [u for u in target.split(",") if u.strip()]
    else:
        raw = knobs.raw("TPUFLOW_FLEET_REPLICAS")
        if raw:
            urls = [u for u in raw.split(",") if u.strip()]
        else:
            directory = knobs.raw("TPUFLOW_FLEET_REGISTRATION_DIR")
            if directory:
                return discover_replicas(directory)
            return []
    out: list[tuple[str, str | None]] = []
    for u in urls:
        for expanded in _expand_dns(_normalize_url(u)):
            out.append((expanded, None))
    return out


def _fetch_status(url: str, timeout_s: float) -> dict:
    """GET ``<url>/status`` → parsed dict. Raises OSError/ValueError on
    anything short of a whole, parseable JSON object — the poller's
    failure path (→ staleness) handles both a dead socket and a
    truncated body identically."""
    import urllib.request

    with urllib.request.urlopen(
        url.rstrip("/") + "/status", timeout=timeout_s
    ) as r:
        body = r.read().decode()
    obj = json.loads(body)  # truncated body → ValueError → stale
    if not isinstance(obj, dict):
        raise ValueError(f"/status returned {type(obj).__name__}")
    return obj


# ---------------------------------------------------------- health score
def health_score(
    status: dict | None,
    *,
    stale: bool,
    slo_delta: int = 0,
    queue_growing: bool = False,
) -> tuple[float, list[str]]:
    """One replica's admission-signal health in [0, 1] with the reasons
    that docked it. Deterministic and host-pure so the future router
    can rank replicas from a fleet snapshot alone:

    - ``stale``         → 0.0 (unreachable/torn /status: never route to it)
    - ``nonfinite``     → −0.5 (NaN/Inf in its exported numbers, or
      nonfinite_steps > 0: its gauges cannot be trusted)
    - ``slo_violating`` → −0.25 (new SLO violations since the last poll)
    - ``queue_growing`` → −0.25 (queue depth rose across recent polls:
      the replica is falling behind its arrivals)
    """
    if stale or status is None:
        return 0.0, ["stale"]
    score, reasons = 1.0, []
    nonfinite = int(status.get("nonfinite_steps", 0) or 0) > 0
    if not nonfinite:
        for k, v in status.items():
            if isinstance(v, float) and v != v:  # NaN without math import
                nonfinite = True
                break
    if nonfinite:
        score -= 0.5
        reasons.append("nonfinite")
    if slo_delta > 0:
        score -= 0.25
        reasons.append("slo_violating")
    if queue_growing:
        score -= 0.25
        reasons.append("queue_growing")
    return max(score, 0.0), reasons


# ------------------------------------------------------------ aggregation
def _sum_key(statuses: list[dict], key: str) -> float | None:
    vals = [
        s[key] for s in statuses if isinstance(s.get(key), (int, float))
    ]
    return sum(vals) if vals else None


def aggregate(statuses: list[dict]) -> dict[str, Any]:
    """Fold fresh replica ``/status`` dicts into the fleet view: sums
    for load (queue/pages/tokens/requests), occupancy-weighted decode
    utilization (a replica's ledger fraction weighted by its slot
    occupancy — an idle replica must not drag the fleet number), merged
    TTFT/ITL histograms → fleet-exact percentiles, and per-traffic-group
    SLO violation rates."""
    out: dict[str, Any] = {"replicas": len(statuses)}
    for key in (
        "serve_queue_depth", "serve_pages_free", "serve_tokens_per_s",
        "serve_requests", "serve_tokens", "serve_slo_violations",
        "serve_pages_host", "serve_pages_disk", "serve_tier_hits",
    ):
        v = _sum_key(statuses, key)
        if v is not None:
            out[key.replace("serve_", "")] = round(v, 4)
    # Occupancy-weighted decode utilization from the PR 13 ledger.
    wsum = usum = 0.0
    for s in statuses:
        util = s.get("serve_decode_utilization")
        occ = s.get("serve_slot_occupancy")
        if isinstance(util, (int, float)) and isinstance(
            occ, (int, float)
        ):
            w = max(float(occ), 1e-9)
            wsum += w
            usum += w * float(util)
    if wsum > 0:
        out["decode_utilization"] = round(usum / wsum, 4)
    occs = [
        s["serve_slot_occupancy"] for s in statuses
        if isinstance(s.get("serve_slot_occupancy"), (int, float))
    ]
    if occs:
        out["slot_occupancy"] = round(sum(occs) / len(occs), 4)
    # HBM headroom (ISSUE 15): the router's admission constraint is the
    # TIGHTEST replica, so the fleet view carries the max used/peak
    # fraction and the min headroom — a fleet-mean would hide the one
    # replica about to OOM. Keys absent when no replica reports (CPU).
    hbm_fracs = [
        s["hbm_used_frac"] for s in statuses
        if isinstance(s.get("hbm_used_frac"), (int, float))
    ]
    if hbm_fracs:
        out["hbm_used_frac_max"] = round(max(hbm_fracs), 4)
        out["hbm_min_headroom_frac"] = round(
            min(1.0 - f for f in hbm_fracs), 4
        )
    hbm_peaks = [
        s["hbm_peak_frac"] for s in statuses
        if isinstance(s.get("hbm_peak_frac"), (int, float))
    ]
    if hbm_peaks:
        out["hbm_peak_frac_max"] = round(max(hbm_peaks), 4)
    # Fleet-exact latency percentiles: merged histogram counts are the
    # counts of the pooled observations, bit for bit.
    for which in ("ttft", "itl"):
        merged = merge_hists(
            s.get(f"serve_{which}_hist")
            for s in statuses
            if isinstance(s.get(f"serve_{which}_hist"), dict)
        )
        if merged:
            out[f"{which}_hist"] = merged
            p = hist_percentiles(merged)
            if p:
                out[which] = p
    # SLO violation counts + rates split by traffic group.
    by_group: dict[str, int] = {}
    req_group: dict[str, int] = {}
    for s in statuses:
        for g, n in (s.get("serve_slo_by_group") or {}).items():
            try:
                by_group[g] = by_group.get(g, 0) + int(n)
            except (TypeError, ValueError):
                continue
        for g, n in (s.get("serve_requests_by_group") or {}).items():
            try:
                req_group[g] = req_group.get(g, 0) + int(n)
            except (TypeError, ValueError):
                continue
    if by_group or req_group:
        out["slo_by_group"] = dict(sorted(by_group.items()))
        out["requests_by_group"] = dict(sorted(req_group.items()))
        out["slo_rate_by_group"] = {
            g: round(by_group.get(g, 0) / max(req_group.get(g, 0), 1), 4)
            for g in sorted(set(by_group) | set(req_group))
        }
    return out


def append_snapshot(path: str, snapshot: dict) -> bool:
    """Append one fleet snapshot as a JSONL line (post-hoc analysis
    trail); failures are reported via the return value, never raised.

    Multi-writer safe (ISSUE 17): the router's poller and a concurrent
    ``tpu_watch --fleet`` may both point at the same
    ``TPUFLOW_FLEET_SNAPSHOT_PATH``, so the whole line lands in ONE
    O_APPEND write — concurrent appenders interleave snapshots, not
    bytes (the registry's crash-safe idiom), and a crash tears at most
    the final line, which ``read_snapshots`` skips."""
    try:
        data = (json.dumps(snapshot, default=str) + "\n").encode()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        return True
    except OSError:
        return False


def read_snapshots(path: str) -> list[dict]:
    """Every well-formed fleet snapshot in file order. A torn final line
    (an append died mid-write), a corrupt line, or a non-snapshot JSON
    value is skipped — reading a damaged trail never raises."""
    out: list[dict] = []
    try:
        f = open(path, encoding="utf-8", errors="replace")
    except OSError:
        return out
    with f:
        for line in f:
            if not line.endswith("\n"):
                continue  # torn tail: the append died mid-write
            line = line.strip()
            if not line:
                continue
            try:
                snap = json.loads(line)
            except ValueError:
                continue
            if isinstance(snap, dict) and isinstance(
                snap.get("fleet"), dict
            ):
                out.append(snap)
    return out


# ---------------------------------------------------------------- poller
class _Replica:
    __slots__ = (
        "url", "rid", "status", "last_ok", "failures", "next_ok_after",
        "error", "was_stale", "prev_requests", "prev_ts", "prev_slo",
        "queue_trend", "rate",
    )

    def __init__(self, url: str, rid: str | None):
        self.url = url
        self.rid = rid
        self.status: dict | None = None
        self.last_ok: float | None = None
        self.failures = 0
        self.next_ok_after = 0.0
        self.error: str | None = None
        self.was_stale = False
        self.prev_requests: float | None = None
        self.prev_ts: float | None = None
        self.prev_slo: float | None = None
        self.queue_trend = 0
        self.rate: float | None = None

    @property
    def id(self) -> str:
        if self.rid:
            return self.rid
        rep = (self.status or {}).get("replica")
        if isinstance(rep, dict) and rep.get("id"):
            return str(rep["id"])
        return self.url


class FleetObservatory:
    """Discover + poll + aggregate one serving fleet.

    ``fetch`` is injectable (tests drive malformed/truncated payloads
    without sockets); everything else resolves from the
    ``TPUFLOW_FLEET_*`` knobs unless overridden."""

    def __init__(
        self,
        target: str | None = None,
        *,
        timeout_s: float = 2.0,
        stale_s: float | None = None,
        poll_interval_s: float | None = None,
        snapshot_path: str | None = None,
        fetch: Callable[[str, float], dict] | None = None,
    ):
        self.target = target
        self.timeout_s = float(timeout_s)
        self.stale_s = float(
            stale_s if stale_s is not None
            else knobs.get_float_lenient("TPUFLOW_FLEET_STALE_S")
        )
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else knobs.get_float_lenient("TPUFLOW_FLEET_POLL_S")
        )
        self.snapshot_path = (
            snapshot_path
            if snapshot_path is not None
            else knobs.raw("TPUFLOW_FLEET_SNAPSHOT_PATH")
        )
        self._fetch = fetch or _fetch_status
        self._replicas: dict[str, _Replica] = {}

    def discover(self) -> list[_Replica]:
        """Refresh the replica set (new registrations join, vanished
        registrations keep their slot — they age into staleness, which
        is the evidence a router needs; they never silently vanish)."""
        for url, rid in discover_replicas(self.target):
            rep = self._replicas.get(url)
            if rep is None:
                self._replicas[url] = _Replica(url, rid)
            elif rid and not rep.rid:
                rep.rid = rid
        return list(self._replicas.values())

    def _poll_one(self, rep: _Replica, now: float) -> None:
        if now < rep.next_ok_after:
            return  # backing off after consecutive failures
        try:
            status = self._fetch(rep.url, self.timeout_s)
        except (OSError, ValueError) as e:
            # Dead socket, HTTP error, or a torn/mid-write JSON body:
            # count the failure, back off, and let staleness marking
            # carry the evidence — the watcher itself never dies.
            rep.failures += 1
            rep.error = f"{type(e).__name__}: {e}"[:200]
            rep.next_ok_after = now + min(
                self.poll_interval_s * (2 ** (rep.failures - 1)), 60.0
            )
            return
        prev_q = (rep.status or {}).get("serve_queue_depth")
        rep.failures = 0
        rep.next_ok_after = 0.0
        rep.error = None
        # Per-replica request rate (fleet QPS term) from consecutive
        # successful polls of the cumulative completion counter.
        reqs = status.get("serve_requests")
        if isinstance(reqs, (int, float)):
            if rep.prev_requests is not None and now > (rep.prev_ts or 0):
                rep.rate = max(
                    (float(reqs) - rep.prev_requests)
                    / (now - rep.prev_ts),
                    0.0,
                )
            rep.prev_requests = float(reqs)
            rep.prev_ts = now
        q = status.get("serve_queue_depth")
        if isinstance(q, (int, float)) and isinstance(
            prev_q, (int, float)
        ):
            rep.queue_trend = (
                rep.queue_trend + 1 if q > prev_q else 0
            )
        rep.status = status
        rep.last_ok = now

    def poll(self) -> dict[str, Any]:
        """One sweep: discover, poll every replica, aggregate, persist.
        Returns the fleet snapshot (also appended to the snapshot JSONL
        when configured)."""
        with _rec.span("fleet.poll", replicas=len(self._replicas)):
            reps = self.discover()
            now = time.monotonic()
            for rep in reps:
                self._poll_one(rep, now)
            snapshot = self.snapshot(now=time.monotonic())
        _rec.gauge(
            "fleet.size",
            snapshot["fleet"]["replicas"],
            healthy=snapshot["fleet"]["healthy"],
        )
        if snapshot["fleet"].get("qps") is not None:
            _rec.gauge("fleet.qps", snapshot["fleet"]["qps"])
        if self.snapshot_path:
            append_snapshot(self.snapshot_path, snapshot)
        return snapshot

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        """The current fleet view without re-polling (poll() calls this;
        consumers wanting a fresh sweep call poll())."""
        if now is None:
            now = time.monotonic()
        rows: list[dict] = []
        fresh: list[dict] = []
        rates: list[float] = []
        for rep in self._replicas.values():
            stale = (
                rep.last_ok is None
                or (now - rep.last_ok) > self.stale_s
            )
            if stale and not rep.was_stale:
                _rec.event(
                    "fleet.replica_stale",
                    replica=rep.id,
                    url=rep.url,
                    age_s=(
                        None if rep.last_ok is None
                        else round(now - rep.last_ok, 3)
                    ),
                    error=rep.error,
                )
            rep.was_stale = stale
            slo_now = (rep.status or {}).get("serve_slo_violations")
            slo_delta = 0
            if isinstance(slo_now, (int, float)):
                if rep.prev_slo is not None:
                    slo_delta = int(slo_now - rep.prev_slo)
                rep.prev_slo = float(slo_now)
            score, reasons = health_score(
                rep.status,
                stale=stale,
                slo_delta=slo_delta,
                queue_growing=rep.queue_trend >= 2,
            )
            row: dict[str, Any] = {
                "id": rep.id,
                "url": rep.url,
                "stale": stale,
                "health": round(score, 3),
                "health_reasons": reasons,
                "queue_trend": rep.queue_trend,
            }
            if rep.last_ok is not None:
                row["age_s"] = round(now - rep.last_ok, 3)
            if rep.error:
                row["error"] = rep.error
            if rep.rate is not None:
                row["qps"] = round(rep.rate, 3)
            if rep.status is not None:
                rep_ident = rep.status.get("replica")
                if isinstance(rep_ident, dict):
                    row["replica"] = rep_ident
                for key in (
                    "serve_queue_depth", "serve_slot_occupancy",
                    "serve_tokens_per_s", "serve_requests",
                    "serve_slo_violations", "serve_pages_free",
                    "serve_decode_utilization", "serve_idle_fraction",
                    "serve_decode_fraction", "serve_ttft_p99_s",
                    "serve_itl_p99_s", "serve_draining",
                    "serve_role", "serve_pages_host", "serve_pages_disk",
                    "serve_tier_hits",
                    "generate_url", "uptime_s",
                    "step", "mfu", "hbm_used_frac", "hbm_peak_frac",
                ):
                    if key in rep.status:
                        row[key] = rep.status[key]
            rows.append(row)
            if not stale and rep.status is not None:
                fresh.append(rep.status)
                if rep.rate is not None:
                    rates.append(rep.rate)
        fleet = aggregate(fresh)
        fleet["replicas"] = len(rows)
        fleet["healthy"] = sum(
            1 for r in rows if not r["stale"] and r["health"] >= 0.5
        )
        fleet["stale"] = sum(1 for r in rows if r["stale"])
        if rates:
            fleet["qps"] = round(sum(rates), 3)
        fleet["min_health"] = min(
            (r["health"] for r in rows), default=0.0
        )
        return {"ts": time.time(), "fleet": fleet, "replicas": rows}


class FleetPoller:
    """Background sweep loop over a :class:`FleetObservatory`.

    The front-door router requires a CHEAP ``snapshot_fn`` —
    ``observatory.poll()`` is a synchronous HTTP sweep of every replica
    and must never run on the routing path (an unresponsive /status
    would stall admission exactly when the fleet is degraded). The
    poller owns that sweep on a daemon thread (``interval_s``, default
    the observatory's poll cadence) and hands consumers
    :meth:`snapshot`: the last COMPLETED sweep, a dict handoff under a
    lock — microseconds, never a round-trip. One synchronous sweep runs
    at construction so the first consumer already sees a populated
    fleet."""

    def __init__(
        self,
        observatory: FleetObservatory,
        interval_s: float | None = None,
    ):
        self.observatory = observatory
        self.interval_s = float(
            interval_s if interval_s is not None
            else observatory.poll_interval_s
        )
        self._lock = threading.Lock()
        self._snap: dict[str, Any] = {}
        self._stop = threading.Event()
        self._sweep()
        self._thread = threading.Thread(
            target=self._run, name="tpuflow-fleet-poller", daemon=True
        )
        self._thread.start()

    def _sweep(self) -> None:
        try:
            snap = self.observatory.poll()
        except Exception:  # noqa: BLE001 — a bad sweep must not kill
            return  # the loop; consumers keep the last good snapshot
        with self._lock:
            self._snap = snap

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sweep()

    def snapshot(self) -> dict[str, Any]:
        """The last completed sweep (``{}`` until one succeeds)."""
        with self._lock:
            return self._snap

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# ------------------------------------------------------------- rendering
def _fmt(v, spec="{:.3g}") -> str:
    return spec.format(v) if isinstance(v, (int, float)) else "-"


def format_fleet_line(fleet: dict) -> str:
    """The one-line fleet headline tpu_watch --fleet and fleet-summary
    share."""
    t = (fleet.get("ttft") or {})
    i = (fleet.get("itl") or {})
    line = (
        f"fleet n={fleet.get('replicas', 0)} "
        f"healthy={fleet.get('healthy', 0)} "
        f"stale={fleet.get('stale', 0)} "
        f"qps={_fmt(fleet.get('qps'))} "
        f"tok/s={_fmt(fleet.get('tokens_per_s'), '{:.0f}')} "
        f"q={_fmt(fleet.get('queue_depth'), '{:.0f}')} "
        f"util={_fmt(fleet.get('decode_utilization'), '{:.2f}')} "
        f"ttft99={_fmt(t.get('p99'), '{:.3f}')}s "
        f"itl99={_fmt(i.get('p99'), '{:.4f}')}s "
        f"slo={_fmt(fleet.get('slo_violations'), '{:.0f}')}"
    )
    if fleet.get("hbm_used_frac_max") is not None:
        # Device observatory (ISSUE 15): the tightest replica's HBM.
        line += (
            f" hbm={_fmt(fleet.get('hbm_used_frac_max'), '{:.2f}')}"
            f"/{_fmt(fleet.get('hbm_peak_frac_max'), '{:.2f}')}pk"
        )
    return line


def format_replica_line(row: dict) -> str:
    """One babysitter line per replica."""
    health = f"{row.get('health', 0.0):.2f}"
    reasons = ",".join(row.get("health_reasons") or ())
    if reasons:
        health += f"({reasons})"
    base = f"  {row.get('id', '?')}: health={health}"
    if row.get("stale"):
        err = f" [{row['error']}]" if row.get("error") else ""
        return base + f" STALE age={_fmt(row.get('age_s'), '{:.1f}')}s" + err
    line = base + (
        f" q={_fmt(row.get('serve_queue_depth'), '{:.0f}')} "
        f"occ={_fmt(row.get('serve_slot_occupancy'), '{:.2f}')} "
        f"tok/s={_fmt(row.get('serve_tokens_per_s'), '{:.0f}')} "
        f"ttft99={_fmt(row.get('serve_ttft_p99_s'), '{:.3f}')}s "
        f"slo={_fmt(row.get('serve_slo_violations'), '{:.0f}')} "
        f"done={_fmt(row.get('serve_requests'), '{:.0f}')}"
    )
    if row.get("hbm_used_frac") is not None:
        line += f" hbm={_fmt(row.get('hbm_used_frac'), '{:.2f}')}"
    return line
