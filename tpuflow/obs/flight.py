"""Crash-forensics flight recorder.

A gang member that dies fatally — an unhandled exception, the
supervisor's SIGTERM→SIGKILL escalation, an injected
``faults.step_boundary`` death — used to leave only a log tail behind.
Every recorder-enabled process now keeps a bounded ring of its last N
events (``Recorder._ring``, ``TPUFLOW_OBS_FLIGHT_RING``, default 256);
``dump_flight`` snapshots that ring plus the process's env/config
fingerprint and the faulting stack into ``<obs_dir>/flight/p<proc>.json``
— a structured artifact the gang supervisor references from its
``flow.member_failed`` event, so triage starts from WHAT the member was
doing, not from grepping its log.

Signal-safety: the SIGTERM hook calls ``dump_flight`` from a signal
handler, which may have interrupted a frame holding the recorder's
buffer lock. The ring snapshot therefore tries the lock with a timeout
and degrades to a best-effort lockless copy; the locked recorder APIs
(the ``obs.flight`` marker event + flush) run only when the lock was
provably free. The dump itself never raises — forensics must not turn a
dying process's exit path into a second failure.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

from tpuflow.obs import recorder as _rec

FLIGHT_SUBDIR = "flight"
# The config surface worth fingerprinting: every knob that changes what
# the process was doing when it died. Values are truncated — fingerprint,
# not dump.
_ENV_PREFIXES = ("TPUFLOW_", "JAX_", "XLA_")


def flight_path(obs_dir: str, proc: int) -> str:
    """Where process ``proc``'s flight dump lands under ``obs_dir`` —
    shared with the supervisor, which looks the artifact up by member
    index when it records the failure."""
    return os.path.join(obs_dir, FLIGHT_SUBDIR, f"p{int(proc):05d}.json")


def _fingerprint() -> dict[str, str]:
    return {
        k: v[:200]
        for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES)
    }


def dump_flight(reason: str, exc: BaseException | None = None) -> str | None:
    """Write this process's flight dump; returns its path, or None when
    telemetry is disabled (no recorder → no ring, and no obs dir to land
    the artifact in) or the write itself failed. Atomic (tmp + rename):
    a reader never sees a torn dump; repeated dumps keep the newest."""
    rec = _rec.recorder()
    if rec is None:
        return None
    try:
        if exc is not None:
            stack = "".join(
                traceback.format_exception(type(exc), exc, exc.__traceback__)
            )
        else:
            stack = "".join(traceback.format_stack())
        ring, lock_was_free = rec._ring_snapshot()
        payload = {
            "reason": reason,
            "ts": time.time(),
            "proc": rec.proc,
            "pid": os.getpid(),
            "attempt": rec.attempt,
            "argv": list(sys.argv),
            "env": _fingerprint(),
            "stack": stack[-8000:],
            "dropped_events": rec.dropped,
            "events": ring,
        }
        path = flight_path(rec.directory, rec.proc)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=_rec._jsonable)
        os.replace(tmp, path)
        if lock_was_free:
            # Safe to touch the locked recorder API: the interrupted
            # frame (if any) did not hold the buffer lock.
            _rec.event("obs.flight", reason=reason, path=path)
            _rec.flush()
        return path
    except Exception:
        return None
