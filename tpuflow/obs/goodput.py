"""Goodput ledger: where did the run's wall-clock go?

tpuflow now survives requeues, rollbacks, and emergency saves (ISSUEs
2-5) — which makes "what fraction of wall time actually trained" the
operator's first question, and one no single event can answer. This
module stitches the merged telemetry stream (``tpuflow.obs.timeline``)
across gang members, attempts, and requeues into ONE per-run accounting:

- ``compute_goodput(events)`` — the authoritative, event-derived ledger.
  Wall time (first event → last event) is decomposed into labeled
  buckets by an interval sweep: every instant is charged to exactly one
  bucket, so the buckets sum to the measured wall by construction
  (residual time lands in ``other``, never vanishes).

- ``ProcessLedger`` / ``live()`` — the incremental, in-process view fed
  by the fences ``StepClock`` already pays (no new synchronization):
  cumulative productive seconds, rolling step/token rates, rolling MFU
  from the model's FLOP estimate, goodput-so-far. This is what the live
  export endpoint (``tpuflow.obs.export``) serves mid-run.

Bucket semantics (highest sweep priority first):

- ``requeue_gap`` — wall time between one launch attempt's last event
  and the next attempt's first (process teardown, backoff, relaunch,
  re-rendezvous). Attempts are identified by the ``launch`` field the
  recorder stamps from ``TPUFLOW_ATTEMPT`` into every gang-member event
  (a dedicated key: the head's ``flow.step`` span carries its own
  ``attempt`` attribute spanning ALL launches, which must not collapse
  the lanes).
- ``resize``      — ``flow.gang_resize`` spans (ISSUE 7): an elastic mesh
  re-form, announce → every survivor joined the new generation. The
  restore/recompile the re-formed members pay inside that window charges
  here (resize outranks them in the sweep), so "what did the shrink
  cost" is one number. An elastic resize produces NO requeue_gap — that
  is the point.
- ``compile``     — ``train.compile`` spans (cold jit trace + compile).
- ``restore``     — ``ckpt.restore`` spans.
- ``data_wait``   — consumer-visible input stalls (``data.host_wait_s``
  gauges / ``data.batch_wait_s`` observations). Carved OUT of the step
  interval that contains them: a step that blocked on input was not
  fully productive.
- ``replay``      — steps re-executed after a divergence rollback
  (``health.rollback`` carries ``from_step``−``step`` = the count of
  discarded steps; the next that-many step observations from that
  process re-cover old ground).
- ``step``        — settled ``train.step_s`` fences: the productive
  bucket, the numerator of the goodput fraction.
- ``ckpt``        — the EXPOSED (non-overlapped) part of ``ckpt.save`` /
  ``ckpt.upload`` spans. Async saves that fully hide behind training
  charge nothing here — that is the point of the async saver.
- ``other``       — everything else (setup, validation, host overhead).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Iterable

from tpuflow.obs import fleet as _fleet
from tpuflow.obs import recorder as _rec

# Sweep priority, highest first: when labeled intervals overlap, each
# instant of wall time is charged to the highest-priority label covering
# it — so a data wait inside a step fence reduces the step bucket, while
# an async checkpoint save hiding under compute charges nothing.
_PRIORITY = (
    "requeue_gap",
    "resize",
    "compile",
    "restore",
    "data_wait",
    "replay",
    "step",
    "ckpt",
)
BUCKETS: tuple[str, ...] = _PRIORITY + ("other",)


def compute_goodput(events: Iterable[dict]) -> dict[str, Any]:
    """Fold a (merged) event stream into the per-run goodput ledger.

    Returns::

        {"wall_s": float,          # first event → last event
         "fraction": float,        # buckets["step"] / wall_s
         "buckets": {bucket: seconds, ...},   # sums exactly to wall_s
         "attempts": [{"attempt", "start_s", "dur_s", "procs"}, ...],
         "steps_timed": int}

    Tolerant of partial streams (a still-running or crashed run): any
    event without a usable timestamp is skipped, unknown names are
    ignored, and an empty stream yields an all-zero ledger.
    """
    evs = sorted(
        (e for e in events if isinstance(e.get("ts"), (int, float))),
        key=lambda e: (e.get("ts", 0.0), e.get("proc", 0)),
    )
    intervals: list[tuple[float, float, str]] = []
    pending_replay: dict[int, int] = {}
    lanes: dict[int, list] = {}  # attempt -> [start, end, procs]
    steps_timed = 0
    t_lo: float | None = None
    t_hi: float | None = None

    for ev in evs:
        ts = float(ev["ts"])
        kind = ev.get("kind")
        name = ev.get("name")
        try:
            proc = int(ev.get("proc", 0) or 0)
        except (TypeError, ValueError):
            proc = 0
        try:
            dur = max(float(ev.get("dur_s", 0.0) or 0.0), 0.0)
        except (TypeError, ValueError):
            dur = 0.0
        end = ts + dur
        t_lo = ts if t_lo is None else min(t_lo, ts)
        t_hi = end if t_hi is None else max(t_hi, end)

        launch = ev.get("launch")
        if launch is not None:
            try:
                a = int(launch)
            except (TypeError, ValueError):
                a = None
            if a is not None:
                lane = lanes.setdefault(a, [ts, end, set()])
                lane[0] = min(lane[0], ts)
                lane[1] = max(lane[1], end)
                lane[2].add(proc)

        if kind == "histogram" and name == "train.step_s":
            try:
                v = max(float(ev.get("value", 0.0) or 0.0), 0.0)
            except (TypeError, ValueError):
                v = 0.0
            label = "step"
            if pending_replay.get(proc, 0) > 0:
                pending_replay[proc] -= 1
                label = "replay"
            # The observation is recorded AT the fence; the interval it
            # measures ends there.
            intervals.append((ts - v, ts, label))
            t_lo = min(t_lo, ts - v)
            steps_timed += 1
        elif kind == "span" and name == "flow.gang_resize":
            intervals.append((ts, end, "resize"))
        elif kind == "span" and name == "train.compile":
            intervals.append((ts, end, "compile"))
        elif kind == "span" and name == "ckpt.restore":
            intervals.append((ts, end, "restore"))
        elif kind == "span" and name in ("ckpt.save", "ckpt.upload"):
            intervals.append((ts, end, "ckpt"))
        elif name in ("data.host_wait_s", "data.batch_wait_s") and kind in (
            "gauge",
            "histogram",
        ):
            try:
                v = max(float(ev.get("value", 0.0) or 0.0), 0.0)
            except (TypeError, ValueError):
                v = 0.0
            if v > 0.0:
                intervals.append((ts - v, ts, "data_wait"))
                t_lo = min(t_lo, ts - v)
        elif kind == "event" and name == "health.rollback":
            try:
                replayed = int(ev.get("from_step", 0) or 0) - int(
                    ev.get("step", 0) or 0
                )
            except (TypeError, ValueError):
                replayed = 0
            if replayed > 0:
                pending_replay[proc] = pending_replay.get(proc, 0) + replayed

    empty = {
        "wall_s": 0.0,
        "fraction": 0.0,
        "buckets": {b: 0.0 for b in BUCKETS},
        "attempts": [],
        "steps_timed": 0,
    }
    if t_lo is None or t_hi is None or t_hi <= t_lo:
        return empty

    # Inter-attempt requeue gaps: uncovered wall between one attempt
    # lane's envelope end and the next lane's start.
    ordered = sorted(lanes.items(), key=lambda kv: kv[1][0])
    attempts_out = [
        {
            "attempt": a,
            "start_s": round(lane[0] - t_lo, 6),
            "dur_s": round(lane[1] - lane[0], 6),
            "procs": sorted(lane[2]),
        }
        for a, lane in ordered
    ]
    for (_a0, l0), (_a1, l1) in zip(ordered, ordered[1:]):
        if l1[0] > l0[1]:
            intervals.append((l0[1], l1[0], "requeue_gap"))

    # Priority sweep: charge each elementary segment of [t_lo, t_hi] to
    # the highest-priority label active over it; uncovered time → other.
    marks: list[tuple[float, int, str]] = []
    for s, e, label in intervals:
        s, e = max(s, t_lo), min(e, t_hi)
        if e > s:
            marks.append((s, 0, label))
            marks.append((e, 1, label))
    marks.sort(key=lambda m: (m[0], m[1]))
    buckets = {b: 0.0 for b in BUCKETS}
    active = {label: 0 for label in _PRIORITY}
    prev = t_lo
    for t, closing, label in marks:
        seg = t - prev
        if seg > 0:
            for b in _PRIORITY:
                if active[b] > 0:
                    buckets[b] += seg
                    break
            else:
                buckets["other"] += seg
            prev = t
        active[label] += -1 if closing else 1
    tail = t_hi - prev
    if tail > 0:
        # No marks can be open past the last close; residual is other.
        buckets["other"] += tail

    wall = t_hi - t_lo
    return {
        "wall_s": wall,
        "fraction": buckets["step"] / wall if wall > 0 else 0.0,
        "buckets": buckets,
        "attempts": attempts_out,
        "steps_timed": steps_timed,
    }


# --------------------------------------------------------- live ledger
# bf16 peak FLOP/s per chip for the rolling-MFU gauge, matched (in
# order) against jax.devices()[0].device_kind — the same table bench.py
# uses for its one-shot MFU leg, duplicated here because runtime code
# must not import the benchmark harness.
_PEAK_FLOPS = (
    ("v6 lite", 918e12),
    ("v6lite", 918e12),
    ("v6e", 918e12),
    ("v5 lite", 197e12),
    ("v5lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
)
_UNSET = object()
_PEAK_CACHE: Any = _UNSET


def _peak_flops_per_device() -> float | None:
    """bf16 peak FLOP/s of the local accelerator, or None off-TPU (the
    rolling MFU is then omitted rather than invented)."""
    global _PEAK_CACHE
    if _PEAK_CACHE is not _UNSET:
        return _PEAK_CACHE
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            _PEAK_CACHE = None
        else:
            kind = dev.device_kind.lower()
            _PEAK_CACHE = next(
                (v for k, v in _PEAK_FLOPS if k in kind), 197e12
            )
    except Exception:
        _PEAK_CACHE = None
    return _PEAK_CACHE


class ProcessLedger:
    """Incremental per-process goodput accounting, fed at the fences the
    hot loop already pays (``StepClock``) and by ``TrainContext.report``.
    The live export endpoint serves ``snapshot()``; the authoritative
    run-level numbers come from ``compute_goodput`` over the merged
    stream — this object exists so ``/metrics`` can answer mid-run
    without re-reading any file."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        """Restart the accounting (called at train-leg start)."""
        self._t0 = time.monotonic()
        self.started_ts = time.time()
        self.steps = 0
        self.tokens = 0
        self.reports = 0
        self.step = 0
        self.productive_s = 0.0
        self.compile_s = 0.0
        self.flops_per_token: float | None = None
        self.health: dict[str, float] = {}
        self.nonfinite_steps = 0
        # Device observatory (ISSUE 15): the latest throttled HBM poll
        # (tpuflow.obs.device.maybe_emit_hbm feeds these at the fences
        # the loops already pay). None = no device has reported — the
        # snapshot omits the hbm_* keys entirely (CPU backends).
        self.hbm_used_bytes: int | None = None
        self.hbm_peak_bytes: int | None = None
        self.hbm_limit_bytes: int | None = None
        # Serving view (tpuflow.infer.serve feeds these each scheduler
        # iteration); zero serve_max_slots = no engine in this process,
        # and the snapshot omits the serve_* keys entirely.
        self.serve_requests = 0
        self.serve_tokens = 0
        self.serve_queue_depth = 0
        self.serve_live_slots = 0
        self.serve_max_slots = 0
        # Drain flag (ISSUE 17): set by serve_forever the moment SIGTERM
        # flips it to admit=False, exported on /status so the front-door
        # router stops admitting to this replica BEFORE it goes dark.
        self.serve_draining = False
        # Forwarding address (ISSUE 17): the replica-side /generate URL
        # (serve_forever's ReplicaGateway), exported verbatim on
        # /status — the fleet row copies it and http_forward POSTs to
        # it. None = no gateway, the row is status-only.
        self.serve_generate_url: str | None = None
        # Paged-KV view (ISSUE 11): page-pool headroom, prefix-cache
        # reuse, and speculative acceptance — zero serve_pages_total =
        # a contiguous (non-paged) engine, keys omitted.
        self.serve_pages_free = 0
        self.serve_pages_total = 0
        self.serve_prefix_hits = 0
        self.serve_prefix_lookups = 0
        self.serve_spec_committed = 0
        self.serve_spec_forwards = 0
        # Disaggregated serving (ISSUE 19): the engine's phase role
        # ("prefill" / "decode" / "both" — placement advice the router
        # reads off the fleet row) and the tiered prefix cache's
        # lower-tier page counts. Role "both" with no tier pages is the
        # classic engine; the serve_role key is exported whenever an
        # engine runs, the tier keys only when a tier is armed.
        self.serve_role: str | None = None
        self.serve_pages_host = 0
        self.serve_pages_disk = 0
        self.serve_tier_hits = 0
        self.serve_tiers_armed = False
        # Serving observatory (ISSUE 13): engine-time ledger fractions,
        # efficiency gauges, and declared-SLO violation count, fed by
        # the engine each scheduler iteration; ITL observations ride a
        # bounded deque exactly like the TTFTs.
        self.serve_ledger_fractions: dict[str, float] = {}
        self.serve_decode_utilization: float | None = None
        self.serve_masked_row_waste: float | None = None
        self.serve_slo_violations = 0
        # Fleet observatory (ISSUE 14): cumulative fixed-edge TTFT/ITL
        # histograms beside the windowed percentile reservoirs — bucket
        # counts are never dropped, so summing them across replicas
        # reproduces the pooled distribution exactly (the windowed
        # gauges below answer "now", the buckets answer "the fleet").
        # Plus the per-traffic-group SLO/request splits the fleet SLO
        # rates aggregate over.
        _edges = _fleet.resolve_hist_edges()
        self._serve_ttft_hist = _fleet.MergeableHistogram(_edges)
        self._serve_itl_hist = _fleet.MergeableHistogram(_edges)
        self.serve_slo_by_group: dict[str, int] = {}
        self.serve_requests_by_group: dict[str, int] = {}
        self._serve_itls: collections.deque = collections.deque(maxlen=2048)
        self._serve_ttfts: collections.deque = collections.deque(maxlen=512)
        self._serve_recent: collections.deque = collections.deque(maxlen=128)
        # (monotonic, cumulative steps+reports, cumulative tokens) marks
        # for the rolling rates: the window spans the last 128 fences.
        self._recent: collections.deque = collections.deque(maxlen=128)
        self._mark()

    def _mark(self) -> None:
        self._recent.append(
            (time.monotonic(), self.steps + self.reports, self.tokens)
        )

    def set_model_flops_per_token(self, flops: float | None) -> None:
        """The model's FLOP/token estimate (dense transformer: 6·N) —
        the numerator of the rolling MFU gauge."""
        self.flops_per_token = float(flops) if flops else None

    def note_compile(self, dur_s: float) -> None:
        self.compile_s += max(float(dur_s), 0.0)
        self._mark()

    def note_step(
        self, dur_s: float, tokens: int = 0, step: int | None = None
    ) -> None:
        self.steps += 1
        self.tokens += int(tokens)
        self.productive_s += max(float(dur_s), 0.0)
        if step is not None:
            try:
                self.step = int(step)
            except (TypeError, ValueError):
                pass
        self._mark()

    def note_report(self, step: int, loss: float | None = None) -> None:
        """A ``TrainContext.report`` fence (custom Trainer loops have no
        StepClock; the report cadence is their liveness signal)."""
        self.reports += 1
        try:
            self.step = max(self.step, int(step))
        except (TypeError, ValueError):
            pass
        if isinstance(loss, (int, float)):
            self.health["loss"] = float(loss)
        self._mark()

    def note_device_hbm(
        self,
        used: int | None,
        peak: int | None,
        limit: int | None,
    ) -> None:
        """One HBM poll (tpuflow.obs.device): bytes in use / peak on the
        busiest local device, limit of the tightest. Peak is kept as a
        running max so a between-polls spike the runtime reported once
        is never lost from the snapshot."""
        if used is not None:
            self.hbm_used_bytes = int(used)
        if peak is not None:
            self.hbm_peak_bytes = max(int(peak), self.hbm_peak_bytes or 0)
        if limit is not None:
            self.hbm_limit_bytes = int(limit)

    def note_health(
        self, loss: float, grad_norm: float, nonfinite: bool
    ) -> None:
        self.health["loss"] = float(loss)
        self.health["grad_norm"] = float(grad_norm)
        if nonfinite:
            self.nonfinite_steps += 1

    # ------------------------------------------------------------- serving
    def note_serve_state(
        self, queue_depth: int, live_slots: int, max_slots: int
    ) -> None:
        """One serving-scheduler iteration's instantaneous state."""
        self.serve_queue_depth = int(queue_depth)
        self.serve_live_slots = int(live_slots)
        self.serve_max_slots = max(int(max_slots), self.serve_max_slots)

    def note_serve_tokens(self, n: int) -> None:
        if n:
            self.serve_tokens += int(n)
        self._serve_recent.append((time.monotonic(), self.serve_tokens))

    def note_serve_ttft(
        self, ttft_s: float | None, trace_id: str | None = None
    ) -> None:
        if isinstance(ttft_s, (int, float)):
            self._serve_ttfts.append(float(ttft_s))
            self._serve_ttft_hist.observe(float(ttft_s), exemplar=trace_id)

    def note_serve_complete(self, group: str | None = None) -> None:
        self.serve_requests += 1
        if group:
            self.serve_requests_by_group[group] = (
                self.serve_requests_by_group.get(group, 0) + 1
            )

    def note_serve_draining(self, draining: bool = True) -> None:
        """The serve loop entered (or left) its SIGTERM drain: no new
        admissions; the fleet row carries ``serve_draining`` so a router
        re-routes this replica's queued work instead of waiting for
        staleness to prove the death."""
        self.serve_draining = bool(draining)

    def note_serve_generate_url(self, url: str | None) -> None:
        """Advertise (or retract) this replica's /generate endpoint.
        The /status snapshot carries it as ``generate_url``; the fleet
        observatory copies it onto the replica row, which is what the
        front-door router's ``http_forward`` POSTs to."""
        self.serve_generate_url = url if url is None else str(url)

    def note_serve_pages(self, free: int, total: int) -> None:
        """Paged-KV pool headroom (free includes idle-evictable pages)."""
        self.serve_pages_free = int(free)
        self.serve_pages_total = max(int(total), self.serve_pages_total)

    def note_serve_prefix(self, hits: int, lookups: int) -> None:
        """Cumulative shared-prefix page cache hits / lookups."""
        self.serve_prefix_hits = int(hits)
        self.serve_prefix_lookups = int(lookups)

    def note_serve_role(self, role: str) -> None:
        """The engine's disaggregation role (ISSUE 19), exported on
        /status so the router can place prefill vs decode traffic."""
        self.serve_role = str(role)

    def note_serve_tiers(self, host: int, disk: int, hits: int) -> None:
        """Tiered prefix-cache state (ISSUE 19): pages currently parked
        per lower tier plus cumulative lower-tier admission hits."""
        self.serve_tiers_armed = True
        self.serve_pages_host = int(host)
        self.serve_pages_disk = int(disk)
        self.serve_tier_hits = int(hits)

    def note_serve_spec(self, committed: int, forwards: int) -> None:
        """Cumulative speculative tokens committed / per-row verifies."""
        self.serve_spec_committed = int(committed)
        self.serve_spec_forwards = int(forwards)

    def note_serve_itl(
        self, itl_s: float | None, trace_id: str | None = None
    ) -> None:
        """One decode tick's per-token latency observation (tick wall /
        tokens committed) for the live ITL percentiles."""
        if isinstance(itl_s, (int, float)):
            self._serve_itls.append(float(itl_s))
            self._serve_itl_hist.observe(float(itl_s), exemplar=trace_id)

    def note_serve_ledger(
        self,
        fractions: dict[str, float],
        *,
        utilization: float | None = None,
        masked_waste: float | None = None,
        slo_violations: int = 0,
        slo_by_group: dict[str, int] | None = None,
    ) -> None:
        """The engine-time ledger's live view (tpuflow.obs.serve_ledger):
        bucket fractions of serve wall, decode utilization, masked-row
        waste, and the SLO violation counts (total + per traffic group,
        the split the fleet SLO rates aggregate)."""
        self.serve_ledger_fractions = dict(fractions)
        self.serve_decode_utilization = utilization
        self.serve_masked_row_waste = masked_waste
        self.serve_slo_violations = int(slo_violations)
        if slo_by_group is not None:
            self.serve_slo_by_group = dict(slo_by_group)

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time view for the export endpoint. Rolling rates come
        from the recent-fence window; MFU only when both the model FLOP
        estimate and the chip's peak are known."""
        now = time.monotonic()
        wall = max(now - self._t0, 1e-9)
        step_rate = tokens_per_s = None
        if len(self._recent) >= 2:
            t_a, n_a, tok_a = self._recent[0]
            t_b, n_b, tok_b = self._recent[-1]
            dt = t_b - t_a
            if dt > 0:
                step_rate = (n_b - n_a) / dt
                tokens_per_s = (tok_b - tok_a) / dt
        mfu = None
        peak = _peak_flops_per_device()
        if self.flops_per_token and tokens_per_s and peak:
            try:
                import jax

                ndev = max(jax.device_count(), 1)
            except Exception:
                ndev = 1
            mfu = self.flops_per_token * tokens_per_s / (peak * ndev)
        out: dict[str, Any] = {
            "uptime_s": round(wall, 3),
            "started_ts": self.started_ts,
            "steps": self.steps,
            "reports": self.reports,
            "step": self.step,
            "tokens": self.tokens,
            "productive_s": round(self.productive_s, 4),
            "compile_s": round(self.compile_s, 4),
            "goodput_fraction": round(self.productive_s / wall, 4),
            "nonfinite_steps": self.nonfinite_steps,
        }
        # Device observatory (ISSUE 15): HBM residency keys only when a
        # device has reported memory_stats — absent off-TPU, never 0.
        if self.hbm_used_bytes is not None:
            out["hbm_used_bytes"] = self.hbm_used_bytes
        if self.hbm_peak_bytes is not None:
            out["hbm_peak_bytes"] = self.hbm_peak_bytes
        if self.hbm_limit_bytes is not None:
            out["hbm_limit_bytes"] = self.hbm_limit_bytes
            if self.hbm_used_bytes is not None:
                out["hbm_used_frac"] = round(
                    self.hbm_used_bytes / self.hbm_limit_bytes, 4
                )
            if self.hbm_peak_bytes is not None:
                out["hbm_peak_frac"] = round(
                    self.hbm_peak_bytes / self.hbm_limit_bytes, 4
                )
        # Outside the serve_max_slots guard on purpose: the gateway
        # starts before the engine's first scheduler iteration feeds
        # note_serve_state, and the router must be able to forward from
        # the very first fleet poll.
        if self.serve_generate_url:
            out["generate_url"] = self.serve_generate_url
        if self.serve_max_slots:
            out["serve_requests"] = self.serve_requests
            out["serve_tokens"] = self.serve_tokens
            out["serve_queue_depth"] = self.serve_queue_depth
            out["serve_slot_occupancy"] = round(
                self.serve_live_slots / self.serve_max_slots, 4
            )
            if len(self._serve_recent) >= 2:
                t_a, tok_a = self._serve_recent[0]
                t_b, tok_b = self._serve_recent[-1]
                if t_b > t_a:
                    out["serve_tokens_per_s"] = round(
                        (tok_b - tok_a) / (t_b - t_a), 2
                    )
            # Nearest-rank percentiles via the shared pctl so the
            # access-log serve-summary reproduces these exact numbers.
            from tpuflow.obs.serve_ledger import pctl as _pctl

            if self._serve_ttfts:
                ts = sorted(self._serve_ttfts)
                out["serve_ttft_p50_s"] = round(_pctl(ts, 0.50), 6)
                out["serve_ttft_p95_s"] = round(_pctl(ts, 0.95), 6)
                out["serve_ttft_p99_s"] = round(_pctl(ts, 0.99), 6)
            if self._serve_itls:
                its = sorted(self._serve_itls)
                out["serve_itl_p50_s"] = round(_pctl(its, 0.50), 6)
                out["serve_itl_p95_s"] = round(_pctl(its, 0.95), 6)
                out["serve_itl_p99_s"] = round(_pctl(its, 0.99), 6)
            # Engine-time ledger view (ISSUE 13): bucket fractions,
            # efficiency gauges, SLO count — keys only when an engine
            # has fed the ledger at least once.
            for b, v in sorted(self.serve_ledger_fractions.items()):
                out[f"serve_{b}_fraction"] = round(float(v), 4)
            if self.serve_decode_utilization is not None:
                out["serve_decode_utilization"] = round(
                    self.serve_decode_utilization, 4
                )
            if self.serve_masked_row_waste is not None:
                out["serve_masked_row_waste"] = round(
                    self.serve_masked_row_waste, 4
                )
            out["serve_slo_violations"] = self.serve_slo_violations
            if self.serve_draining:
                out["serve_draining"] = True
            # Mergeable histogram view (ISSUE 14): cumulative bucket
            # counts /metrics renders in the Prometheus histogram
            # convention and the fleet observatory SUMS across replicas
            # — the per-replica percentile gauges above cannot merge.
            if self._serve_ttft_hist.count:
                out["serve_ttft_hist"] = self._serve_ttft_hist.to_dict()
            if self._serve_itl_hist.count:
                out["serve_itl_hist"] = self._serve_itl_hist.to_dict()
            if self.serve_slo_by_group:
                out["serve_slo_by_group"] = dict(
                    sorted(self.serve_slo_by_group.items())
                )
            if self.serve_requests_by_group:
                out["serve_requests_by_group"] = dict(
                    sorted(self.serve_requests_by_group.items())
                )
            if self.serve_role is not None:
                out["serve_role"] = self.serve_role
            if self.serve_pages_total:
                out["serve_pages_free"] = self.serve_pages_free
                if self.serve_prefix_lookups:
                    out["serve_prefix_hit_rate"] = round(
                        self.serve_prefix_hits / self.serve_prefix_lookups,
                        4,
                    )
            if self.serve_tiers_armed:
                out["serve_pages_host"] = self.serve_pages_host
                out["serve_pages_disk"] = self.serve_pages_disk
                out["serve_tier_hits"] = self.serve_tier_hits
            if self.serve_spec_forwards:
                out["serve_spec_accept_rate"] = round(
                    self.serve_spec_committed / self.serve_spec_forwards, 4
                )
        if step_rate is not None:
            out["step_rate"] = round(step_rate, 4)
            out["tokens_per_s"] = round(tokens_per_s, 2)
        if mfu is not None:
            out["mfu"] = round(mfu, 4)
        if self.flops_per_token:
            out["flops_per_token"] = self.flops_per_token
        for k, v in self.health.items():
            out[k] = v
        return out


_LEDGER = ProcessLedger()


def live() -> ProcessLedger:
    """This process's live goodput ledger (one per process, reset at
    train-leg start)."""
    return _LEDGER


def emit_gauges() -> None:
    """Record the goodput-so-far gauges into the event stream (called at
    epoch fences and every ~32 steps by ``StepClock``; no-ops when
    telemetry is disabled — the gauge calls check that themselves)."""
    led = _LEDGER
    wall = max(time.monotonic() - led._t0, 1e-9)
    _rec.gauge("goodput.productive_s", round(led.productive_s, 4))
    _rec.gauge(
        "goodput.lost_s", round(max(wall - led.productive_s, 0.0), 4)
    )
    _rec.gauge("goodput.fraction", round(led.productive_s / wall, 4))
