"""Training-health observatory: numerics policy + divergence handling.

The telemetry stream (ISSUE 1) answers "where did time go"; the fault
machinery (ISSUE 2) answers "who died". This module closes the remaining
gap — a run whose *numerics* go bad (NaN'd loss, exploding gradients, a
loss spike after an optimizer misstep) used to burn its remaining budget
producing garbage. Three pieces:

- ``HealthMonitor`` — a host-side policy object fed one observation per
  fenced train step (loss, global grad norm, the fused nonfinite flag
  computed *inside* the jitted step — see ``tpuflow.train.optim.
  health_stats``). Detectors: a consecutive-nonfinite budget, an absolute
  grad-norm explosion threshold, and a rolling median+MAD loss-spike
  test (robust statistics — one earlier spike must not inflate the
  baseline the next one is judged against). On detection it records a
  ``health.anomaly`` event and returns an ``Anomaly`` for the loop to
  act on.

- Divergence handling — ``handle_anomaly`` decides rollback vs halt:
  with rollback enabled (the default) it returns the newest checkpoint
  step whose shards pass crc32 verification (``last_verified_step``,
  reusing the ISSUE 2 integrity machinery) for the loop to restore;
  otherwise it raises ``TrainingDiverged`` with a diagnostic instead of
  letting the run report NaN losses.

- ``ProfileWindow`` — ``TPUFLOW_PROFILE=<start>:<stop>`` wraps exactly
  those train steps in a ``jax.profiler`` trace saved under
  ``<run_dir>/obs/profile/`` and recorded as a ``health.profile`` event
  the timeline card references.

Everything is env-configured (``TPUFLOW_HEALTH*`` knobs, see
``HealthConfig``) so a babysitting policy can be changed per launch
without a code change, and ``TPUFLOW_HEALTH=0`` removes the monitor
entirely — the loops then pay one ``is not None`` check per step.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import statistics
from typing import Any

from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs


@dataclasses.dataclass
class HealthConfig:
    """Env-tunable training-health policy (``TPUFLOW_HEALTH*``).

    - ``TPUFLOW_HEALTH=0``            disable monitoring entirely
    - ``TPUFLOW_HEALTH_ROLLBACK=0``   halt with a diagnostic instead of
                                      auto-rolling-back to the last
                                      verified checkpoint
    - ``TPUFLOW_HEALTH_NAN_BUDGET``   consecutive nonfinite steps that
                                      trip the detector (default 1: the
                                      first NaN/Inf step is an anomaly —
                                      a NaN update has already poisoned
                                      params AND optimizer moments)
    - ``TPUFLOW_HEALTH_WINDOW``       rolling loss window (default 64)
    - ``TPUFLOW_HEALTH_WARMUP``       observations required before the
                                      spike test judges (default 16)
    - ``TPUFLOW_HEALTH_SPIKE_MADS``   spike threshold in robust sigmas
                                      above the window median (default
                                      12; sigma = 1.4826·MAD with a 1 %
                                      -of-median floor so a flat window
                                      can't make any jitter an anomaly)
    - ``TPUFLOW_HEALTH_GRAD_MAX``     absolute grad-norm explosion
                                      threshold (default 0 = off; early
                                      training legitimately spikes, so
                                      this knob is opt-in per run)
    - ``TPUFLOW_HEALTH_MAX_ROLLBACKS`` rollback budget before anomalies
                                      halt anyway (default 2 — a run
                                      that keeps diverging needs a
                                      human, not a loop)
    - ``TPUFLOW_HEALTH_LR_BACKOFF``   LR multiplier applied on each
                                      rollback (default 1.0 = off;
                                      e.g. 0.5 halves the peak LR so
                                      the replayed steps take a gentler
                                      trajectory)
    """

    enabled: bool = True
    rollback: bool = True
    nan_budget: int = 1
    window: int = 64
    warmup: int = 16
    spike_mads: float = 12.0
    grad_norm_max: float = 0.0
    max_rollbacks: int = 2
    lr_backoff: float = 1.0

    @classmethod
    def from_env(cls) -> "HealthConfig":
        return cls(
            enabled=knobs.raw("TPUFLOW_HEALTH", "1")
            not in ("0", "false"),
            rollback=knobs.raw("TPUFLOW_HEALTH_ROLLBACK", "1")
            not in ("0", "false"),
            nan_budget=max(
                1, knobs.get_int_lenient("TPUFLOW_HEALTH_NAN_BUDGET", 1)
            ),
            window=max(
                4, knobs.get_int_lenient("TPUFLOW_HEALTH_WINDOW", 64)
            ),
            warmup=max(
                2, knobs.get_int_lenient("TPUFLOW_HEALTH_WARMUP", 16)
            ),
            spike_mads=knobs.get_float_lenient(
                "TPUFLOW_HEALTH_SPIKE_MADS", 12.0
            ),
            grad_norm_max=knobs.get_float_lenient(
                "TPUFLOW_HEALTH_GRAD_MAX", 0.0
            ),
            max_rollbacks=knobs.get_int_lenient(
                "TPUFLOW_HEALTH_MAX_ROLLBACKS", 2
            ),
            lr_backoff=knobs.get_float_lenient(
                "TPUFLOW_HEALTH_LR_BACKOFF", 1.0
            ),
        )


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One detected divergence: what tripped, at which optimizer step."""

    kind: str          # nonfinite | grad_explosion | loss_spike
    step: int
    detail: dict[str, Any]

    def describe(self) -> str:
        parts = ", ".join(f"{k}={v}" for k, v in sorted(self.detail.items()))
        return f"{self.kind} at step {self.step} ({parts})"


class TrainingDiverged(RuntimeError):
    """Raised when an anomaly cannot (or must not) be rolled back: the
    run halts with a diagnostic instead of reporting NaN losses."""

    def __init__(self, anomaly: Anomaly, *, hint: str = ""):
        self.anomaly = anomaly
        msg = (
            f"training diverged: {anomaly.describe()}. "
            "No rollback was performed"
        )
        if hint:
            msg += f" ({hint})"
        msg += (
            ". Knobs: TPUFLOW_HEALTH_ROLLBACK=1 re-enables checkpoint "
            "rollback, TPUFLOW_HEALTH=0 disables monitoring, "
            "TPUFLOW_HEALTH_NAN_BUDGET / _SPIKE_MADS / _GRAD_MAX tune "
            "the detectors (README: Training health runbook)."
        )
        super().__init__(msg)


class HealthMonitor:
    """Rolling-statistics divergence detector, one instance per run.

    Feed it one observation per fenced step; it returns an ``Anomaly``
    when a detector trips (recording a ``health.anomaly`` event) and
    ``None`` otherwise. Stateless consumers can rebuild the same view
    from the ``health.*`` telemetry — this object exists so the training
    loop itself can act before the run budget burns.
    """

    def __init__(self, cfg: HealthConfig | None = None):
        self.cfg = cfg or HealthConfig.from_env()
        self._window: collections.deque[float] = collections.deque(
            maxlen=self.cfg.window
        )
        self._nan_streak = 0
        self.rollbacks = 0
        self.last: dict[str, float] = {}

    @classmethod
    def from_env(cls) -> "HealthMonitor | None":
        """The run's monitor, or ``None`` when ``TPUFLOW_HEALTH=0`` — the
        disabled path is one ``is not None`` check per step."""
        cfg = HealthConfig.from_env()
        return cls(cfg) if cfg.enabled else None

    # -------------------------------------------------------------- observe
    def observe(
        self,
        step: int,
        loss: float,
        grad_norm: float | None = None,
        nonfinite: bool | None = None,
    ) -> Anomaly | None:
        if nonfinite is None:
            nonfinite = not math.isfinite(loss) or (
                grad_norm is not None and not math.isfinite(grad_norm)
            )
        self.last = {
            "step": step,
            "loss": loss,
            "grad_norm": grad_norm if grad_norm is not None else float("nan"),
        }
        if nonfinite:
            self._nan_streak += 1
            if self._nan_streak >= self.cfg.nan_budget:
                return self._anomaly(
                    "nonfinite", step,
                    loss=loss, grad_norm=grad_norm,
                    streak=self._nan_streak, budget=self.cfg.nan_budget,
                )
            return None
        self._nan_streak = 0
        if (
            self.cfg.grad_norm_max > 0.0
            and grad_norm is not None
            and grad_norm > self.cfg.grad_norm_max
        ):
            return self._anomaly(
                "grad_explosion", step,
                grad_norm=grad_norm, threshold=self.cfg.grad_norm_max,
            )
        if len(self._window) >= self.cfg.warmup:
            med = statistics.median(self._window)
            mad = statistics.median(abs(x - med) for x in self._window)
            # Robust sigma with a relative floor: a perfectly flat window
            # (MAD ~ 0) must not brand ordinary jitter an anomaly.
            sigma = max(1.4826 * mad, 0.01 * abs(med), 1e-6)
            threshold = med + self.cfg.spike_mads * sigma
            if loss > threshold:
                # The spike is NOT appended — the window stays a
                # pre-spike baseline for any follow-up judgment.
                return self._anomaly(
                    "loss_spike", step,
                    loss=loss, median=round(med, 6),
                    threshold=round(threshold, 6), window=len(self._window),
                )
        self._window.append(loss)
        return None

    def rolled_back(self) -> None:
        """Reset transient state after a checkpoint rollback: the streak
        belongs to the discarded trajectory. The loss window is kept —
        it is pre-anomaly history the replayed steps are judged against."""
        self.rollbacks += 1
        self._nan_streak = 0

    def _anomaly(self, kind: str, step: int, **detail) -> Anomaly:
        detail = {
            k: (float(v) if isinstance(v, float) else v)
            for k, v in detail.items()
            if v is not None
        }
        # 'detector' not 'kind': the event schema already uses "kind" for
        # the record type (span/counter/.../event).
        _rec.event("health.anomaly", detector=kind, step=step, **detail)
        return Anomaly(kind=kind, step=step, detail=detail)


# ------------------------------------------------------------- rollback
def last_verified_step(manager) -> int | None:
    """Newest checkpoint step whose shards pass integrity verification
    (``CheckpointManager.verify_step``, ISSUE 2): the only steps a
    divergence rollback may restore — rolling back onto silently
    corrupted weights would trade one failure for a worse one."""
    for step in reversed(manager.all_steps()):
        try:
            if manager.verify_step(step):
                return step
        except (OSError, ValueError):
            continue
    return None


def handle_anomaly(monitor: HealthMonitor, anomaly: Anomaly, manager) -> int:
    """Decide the anomaly's fate: the verified step to roll back to, or
    ``TrainingDiverged`` when policy forbids / budget is exhausted / no
    verified checkpoint exists. The caller restores and records the
    ``health.rollback`` event (it knows the restored state + LR scale)."""
    cfg = monitor.cfg
    if not cfg.rollback:
        raise TrainingDiverged(anomaly, hint="TPUFLOW_HEALTH_ROLLBACK=0")
    if monitor.rollbacks >= cfg.max_rollbacks:
        raise TrainingDiverged(
            anomaly,
            hint=f"rollback budget exhausted "
            f"({monitor.rollbacks}/{cfg.max_rollbacks})",
        )
    target = last_verified_step(manager)
    if target is None:
        raise TrainingDiverged(anomaly, hint="no verified checkpoint to restore")
    monitor.rolled_back()
    return target


class _RollbackSignal(Exception):
    """Internal control flow: unwind the epoch loop to the restore point.
    Carries the verified target step and the anomaly that caused it."""

    def __init__(self, target: int, anomaly: Anomaly):
        self.target = target
        self.anomaly = anomaly
        super().__init__(f"rollback to step {target}: {anomaly.describe()}")


# ------------------------------------------------------ windowed profiler
class ProfileWindow:
    """``TPUFLOW_PROFILE=<start>:<stop>`` — capture a ``jax.profiler``
    trace of exactly train steps ``start..stop`` (1-based optimizer
    steps, inclusive) into ``<obs_dir>/profile/``.

    Windowed on purpose: whole-run traces are huge and skew steady-state
    timing; two steps around a suspected stall are what a babysitter
    actually opens. The capture is best-effort — a profiler failure
    must never fail the run."""

    def __init__(self, start: int, stop: int, out_dir: str):
        self.start = start
        self.stop = stop
        self.out_dir = out_dir
        self._active = False
        self._done = False

    @classmethod
    def from_env(cls, out_dir: str | None = None) -> "ProfileWindow | None":
        spec = knobs.raw("TPUFLOW_PROFILE", "")
        if not spec:
            return None
        try:
            start_s, _, stop_s = spec.partition(":")
            start, stop = int(start_s), int(stop_s or start_s)
        except ValueError:
            print(
                f"[tpuflow] malformed TPUFLOW_PROFILE={spec!r} "
                "(want <startstep>:<stopstep>); profiling disabled"
            )
            return None
        if start < 1 or stop < start:
            print(
                f"[tpuflow] TPUFLOW_PROFILE={spec!r} window is empty; "
                "profiling disabled"
            )
            return None
        if out_dir is None:
            rec = _rec.recorder()
            if rec is not None:
                out_dir = os.path.join(rec.directory, "profile")
            else:
                out_dir = knobs.raw("TPUFLOW_PROFILE_DIR")
        if not out_dir:
            print(
                "[tpuflow] TPUFLOW_PROFILE set but telemetry is disabled "
                "and TPUFLOW_PROFILE_DIR is unset; profiling disabled"
            )
            return None
        return cls(start, stop, out_dir)

    def maybe_start(self, step: int) -> None:
        """Call before executing optimizer step ``step``."""
        if self._active or self._done or step < self.start:
            return
        try:
            import jax

            os.makedirs(self.out_dir, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
            self._active = True
        except Exception as e:
            self._done = True
            print(f"[tpuflow] profiler start failed (ignored): {e!r}")

    def maybe_stop(self, step: int) -> None:
        """Call after fencing optimizer step ``step``."""
        if not self._active or step < self.stop:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            print(f"[tpuflow] profiler stop failed (ignored): {e!r}")
        self._active = False
        self._done = True
        _rec.event(
            "health.profile",
            start_step=self.start, stop_step=self.stop, dir=self.out_dir,
        )

    def close(self) -> None:
        """End-of-run safety net: a window whose stop step was never
        reached (short run, early halt) must not leave the process-wide
        profiler running."""
        if self._active:
            self.maybe_stop(self.stop)


# ------------------------------------------------------------- summaries
_HEALTH_EVENTS = ("health.anomaly", "health.rollback", "health.profile")


def health_summary(events) -> dict[str, Any]:
    """Fold an event stream into the run-health view ``Run.health()``
    serves and ``obs.summarize`` embeds: anomalies/rollbacks/profile
    windows (compact dicts), the last numerics gauges, nonfinite-step
    and dropped-event totals."""
    out: dict[str, Any] = {
        "anomalies": [],
        "rollbacks": [],
        "profiles": [],
        "last": {},
        "nonfinite_steps": 0.0,
        "dropped_events": 0.0,
    }
    for ev in events:
        name = ev.get("name", "")
        kind = ev.get("kind")
        if kind == "event":
            compact = {
                k: v
                for k, v in ev.items()
                if k not in ("kind", "name", "pid")
            }
            if name == "health.anomaly":
                out["anomalies"].append(compact)
            elif name == "health.rollback":
                out["rollbacks"].append(compact)
            elif name == "health.profile":
                out["profiles"].append(compact)
            elif name == "obs.dropped":
                out["dropped_events"] += float(ev.get("value", 0.0))
        elif kind == "counter" and name == "health.nonfinite":
            out["nonfinite_steps"] += float(ev.get("value", 1.0))
        elif kind == "gauge" and name.startswith("health."):
            out["last"][name[len("health."):]] = float(ev.get("value", 0.0))
    return out
