"""Anomaly-triggered profiler capture (ISSUE 15): promote the static
``TPUFLOW_PROFILE=start:stop`` window (PR 3's ``ProfileWindow``) into a
detector-armed flight recorder for the device.

The static window answers "profile steps 120-121" when an operator
already knows where the stall is. Production anomalies don't announce
their step numbers — so :class:`AnomalyCapturer` watches the
observations the loops already produce and arms a **bounded**
``jax.profiler`` trace + device-memory dump the moment one spikes:

- **Step-time detector** — rolling median+MAD spike test over fenced
  train-step wall times (mirrors ``HealthMonitor``'s loss-spike test:
  robust statistics, the spike is never appended to its own baseline).
- **ITL detector** — the same test over the serving engine's per-token
  decode latencies.
- **Direct triggers** — a declared-SLO breach (``serve.slo_violation``
  path) and a nonfinite train step each arm a capture immediately.

**Governor** (flight-recorder discipline): at most one capture in
flight; a cooldown (``TPUFLOW_PROF_COOLDOWN_S``) between captures; a
per-run cap (``TPUFLOW_PROF_MAX_CAPTURES``). A trace spans the next
``TPUFLOW_PROF_TRACE_STEPS`` observations (with a hard wall-clock
bound), then stops and dumps ``device_memory.prof`` beside it — whole-
run traces are huge and skew steady-state timing; two anomalous steps
are what a babysitter actually opens. Every capture is recorded as a
``prof.capture`` event referencing the artifact directory.

Trigger paths run inside the step/decode loops, so every profiler call
is fenced by try/except and a failing backend disables capture for the
run with one printed note — **never fatal, never raising into the hot
loop**. The capture backend and clock are injectable so the governor is
unit-testable without jax or real sleeps.

Disarmed (``TPUFLOW_PROF_TRIGGER`` unset, the default) the whole module
costs the loops one ``is not None`` check per observation — pinned by
the tests/test_obs.py overhead guard.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import statistics
import time
from typing import Any

from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs


@dataclasses.dataclass
class CaptureConfig:
    """Env-tunable capture policy (``TPUFLOW_PROF_*`` knobs)."""

    z_mads: float = 8.0
    cooldown_s: float = 300.0
    max_captures: int = 3
    trace_steps: int = 2
    window: int = 64
    warmup: int = 16
    # Hard wall bound on one capture: a trigger fired from a path that
    # stops producing observations (a draining server) must not leave
    # the process-wide profiler running for the rest of the run.
    max_trace_s: float = 60.0

    @classmethod
    def from_env(cls) -> "CaptureConfig":
        return cls(
            z_mads=knobs.get_float_lenient("TPUFLOW_PROF_ZMADS"),
            cooldown_s=knobs.get_float_lenient("TPUFLOW_PROF_COOLDOWN_S"),
            max_captures=max(
                0, knobs.get_int_lenient("TPUFLOW_PROF_MAX_CAPTURES")
            ),
            trace_steps=max(
                1, knobs.get_int_lenient("TPUFLOW_PROF_TRACE_STEPS")
            ),
        )


class _JaxTracer:
    """The real capture backend, isolated so tests inject a fake."""

    def start(self, out_dir: str) -> None:
        import jax

        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)

    def stop(self) -> None:
        import jax

        jax.profiler.stop_trace()

    def memdump(self, path: str) -> None:
        import jax

        jax.profiler.save_device_memory_profile(path)


class AnomalyCapturer:
    """Detector + governor + bounded capture, one instance per process.

    Feed it observations (``observe_step`` / ``observe_itl``) and direct
    triggers (``note_slo_breach`` / ``note_nonfinite``); it decides when
    a bounded profiler capture is warranted and owns its lifecycle. All
    methods are exception-fenced — see the module docstring."""

    def __init__(
        self,
        out_dir: str,
        cfg: CaptureConfig | None = None,
        *,
        tracer=None,
        clock=time.monotonic,
    ):
        self.cfg = cfg or CaptureConfig.from_env()
        self.out_dir = out_dir
        self._tracer = tracer or _JaxTracer()
        self._clock = clock
        self._step_window: collections.deque = collections.deque(
            maxlen=self.cfg.window
        )
        self._itl_window: collections.deque = collections.deque(
            maxlen=self.cfg.window
        )
        self.captures = 0
        self.suppressed = 0
        self._last_capture_t: float | None = None
        self._active: dict[str, Any] | None = None
        self._broken = False

    # ----------------------------------------------------------- detectors
    def _judge(self, window, v: float) -> float | None:
        """Median+MAD spike test: the threshold when ``v`` spikes, None
        otherwise. Sigma floor mirrors HealthMonitor — a flat window
        must not brand jitter an anomaly."""
        if len(window) < self.cfg.warmup:
            return None
        med = statistics.median(window)
        mad = statistics.median(abs(x - med) for x in window)
        sigma = max(1.4826 * mad, 0.01 * abs(med), 1e-9)
        threshold = med + self.cfg.z_mads * sigma
        return threshold if v > threshold else None

    def observe_step(self, dur_s: float, step: int | None = None) -> None:
        """One fenced train step's wall time. While a capture is live
        this only advances its bound (an anomalous window must not judge
        or re-trigger against itself)."""
        if self._active is not None:
            self._advance()
            return
        threshold = self._judge(self._step_window, dur_s)
        if threshold is not None:
            # The spike is NOT appended — the window stays a pre-spike
            # baseline for any follow-up judgment.
            self.trigger(
                "step_time", value=round(float(dur_s), 6),
                threshold=round(threshold, 6), step=step,
            )
        else:
            self._step_window.append(float(dur_s))

    def observe_itl(self, itl_s: float) -> None:
        """One decode tick's per-token latency (the serving twin)."""
        if self._active is not None:
            self._advance()
            return
        threshold = self._judge(self._itl_window, itl_s)
        if threshold is not None:
            self.trigger(
                "itl", value=round(float(itl_s), 6),
                threshold=round(threshold, 6),
            )
        else:
            self._itl_window.append(float(itl_s))

    def note_slo_breach(self, kind: str) -> None:
        """A declared-SLO violation (ttft | itl) — direct trigger."""
        if self._active is not None:
            return
        self.trigger(f"slo_{kind}")

    def note_nonfinite(self, step: int | None = None) -> None:
        """A nonfinite train step — direct trigger (the numerics went
        bad; the trace shows what the device was doing when they did)."""
        if self._active is not None:
            return
        self.trigger("nonfinite", step=step)

    # ------------------------------------------------------------ governor
    def trigger(self, reason: str, **detail) -> bool:
        """Arm one bounded capture, subject to the governor: no
        concurrent capture, the cooldown since the last, the per-run
        cap. Suppressions are counted, never silent. Returns whether a
        capture started."""
        if self._broken:
            return False
        if self._active is not None:
            self.suppressed += 1
            return False
        if self.captures >= self.cfg.max_captures:
            self.suppressed += 1
            return False
        now = self._clock()
        if (
            self._last_capture_t is not None
            and now - self._last_capture_t < self.cfg.cooldown_s
        ):
            self.suppressed += 1
            return False
        cap_dir = os.path.join(
            self.out_dir, f"capture_{self.captures + 1:02d}_{reason}"
        )
        try:
            self._tracer.start(cap_dir)
        except Exception as e:
            # A broken profiler backend must not re-fail every later
            # trigger (and must never fail the run): disable for good.
            self._broken = True
            print(
                "[tpuflow] triggered profiler capture failed to start "
                f"(capture disabled for this run): {e!r}"
            )
            return False
        self.captures += 1
        self._last_capture_t = now
        self._active = {
            "reason": reason,
            "dir": cap_dir,
            "remaining": max(1, self.cfg.trace_steps),
            "deadline": now + self.cfg.max_trace_s,
            "detail": {k: v for k, v in detail.items() if v is not None},
            "t0": now,
        }
        return True

    def _advance(self) -> None:
        a = self._active
        a["remaining"] -= 1
        if a["remaining"] <= 0 or self._clock() > a["deadline"]:
            self._finish()

    def poll(self) -> None:
        """Deadline check for loops between observations (the serving
        scheduler's periodic fence): finishes a wall-expired capture."""
        if (
            self._active is not None
            and self._clock() > self._active["deadline"]
        ):
            self._finish()

    def _finish(self) -> None:
        a, self._active = self._active, None
        try:
            self._tracer.stop()
        except Exception as e:
            self._broken = True
            print(f"[tpuflow] profiler trace stop failed (ignored): {e!r}")
        mem_path: str | None = os.path.join(a["dir"], "device_memory.prof")
        try:
            self._tracer.memdump(mem_path)
        except Exception:
            # CPU backends have no device memory profile — the trace
            # alone is still the artifact.
            mem_path = None
        _rec.event(
            "prof.capture",
            reason=a["reason"],
            dir=a["dir"],
            capture=self.captures,
            dur_s=round(self._clock() - a["t0"], 4),
            memory_profile=mem_path,
            suppressed_so_far=self.suppressed,
            **a["detail"],
        )

    def close(self) -> None:
        """End-of-run safety net: an in-flight capture must not leave
        the process-wide profiler running."""
        if self._active is not None:
            self._finish()


# ------------------------------------------------------ process singleton
_CAPTURER: AnomalyCapturer | None = None
_CHECKED = False


def maybe_from_env() -> AnomalyCapturer | None:
    """The process's capturer, created once when ``TPUFLOW_PROF_TRIGGER``
    is armed and an output dir resolves (the recorder's ``obs/profile``
    dir, else ``TPUFLOW_PROF_DIR``). None otherwise — callers hold the
    result and pay one ``is not None`` check per observation."""
    global _CAPTURER, _CHECKED
    if _CHECKED:
        return _CAPTURER
    _CHECKED = True
    if not knobs.get_bool("TPUFLOW_PROF_TRIGGER"):
        return None
    out_dir = knobs.raw("TPUFLOW_PROF_DIR")
    if not out_dir:
        rec = _rec.recorder()
        if rec is not None:
            out_dir = os.path.join(rec.directory, "profile")
    if not out_dir:
        print(
            "[tpuflow] TPUFLOW_PROF_TRIGGER set but telemetry is "
            "disabled and TPUFLOW_PROF_DIR is unset; anomaly capture "
            "disabled"
        )
        return None
    _CAPTURER = AnomalyCapturer(out_dir)
    return _CAPTURER


def _reset_for_tests() -> None:
    global _CAPTURER, _CHECKED
    if _CAPTURER is not None:
        try:
            _CAPTURER.close()
        except Exception:
            pass
    _CAPTURER = None
    _CHECKED = False
