"""Process-local telemetry recorder: buffered structured JSONL events.

One ``Recorder`` per process writes ``events.p<proc>.jsonl`` under the
run's ``obs/`` directory (gang members inherit the directory through
``TPUFLOW_OBS_DIR`` and their slot through ``TPUFLOW_OBS_PROC`` /
``TPUFLOW_PROCESS_ID``); ``tpuflow.obs.timeline.merge_run_events`` unions
the per-process files into one run timeline.

Overhead contract (pinned by tests/test_obs.py):

- **Disabled** (no obs dir configured): every API call is one module-level
  boolean check — ``span()`` returns a shared no-op context manager,
  ``counter``/``gauge``/``histogram``/``event`` return immediately. No
  allocation, no locking, no I/O on the hot path.
- **Enabled**: ``record()`` appends a dict to an in-memory buffer under a
  lock — no file I/O on the caller's thread. A daemon thread flushes the
  buffer every ``flush_interval`` seconds (and on ``flush()``/``close()``/
  interpreter exit), so writes happen off the step critical path.

Event schema (one JSON object per line)::

    {"kind": "span|counter|gauge|histogram|event",
     "name": "<catalog name>", "ts": <wall-clock start, s>,
     "proc": <gang process index>, "pid": <os pid>,
     "launch": <launch attempt, gang members only (TPUFLOW_ATTEMPT)>,
     "dur_s": <monotonic duration, spans only>,
     "value": <counter/gauge/histogram payload>, ...attrs}

The ``launch`` stamp (a dedicated key — several flow events already
carry their own ``attempt`` attribute, which must not be confused with
the process's launch number) is what lets the goodput ledger
(``tpuflow.obs.goodput``) stitch a requeued gang's attempts into one
per-run accounting with an explicit inter-attempt requeue-gap bucket.
The recorder also keeps a bounded ring of the most recent events — the
flight recorder's (``tpuflow.obs.flight``) forensic payload when the
process dies on a fatal path.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time
from typing import Any
from tpuflow.utils import knobs

_ENABLED = False
_RECORDER: "Recorder | None" = None
_ENV_CHECKED = False
_LOCK = threading.Lock()


class _NoopSpan:
    """Shared, reentrant no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):  # matches _Span.set
        return self


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_rec", "_name", "_attrs", "_t0", "_ts")

    def __init__(self, rec: "Recorder", name: str, attrs: dict):
        self._rec = rec
        self._name = name
        self._attrs = attrs

    def set(self, **attrs):
        """Attach attributes discovered mid-span (e.g. bytes moved)."""
        self._attrs.update(attrs)
        return self

    def __enter__(self):
        self._ts = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, *exc):
        dur = time.monotonic() - self._t0
        if exc_type is not None:
            self._attrs.setdefault("error", exc_type.__name__)
        self._rec.record(
            "span", self._name, ts=self._ts, dur_s=dur, **self._attrs
        )
        return False


# Buffer bound: a wedged disk (every append raising OSError) must not let
# the in-memory buffer grow without limit for the rest of the run. Beyond
# the cap events are counted, not kept — and the count is surfaced as a
# final ``obs.dropped`` event at close, so loss is visible, never silent.
_DEFAULT_MAX_BUFFERED = 65536

# Flight-recorder ring: the last N events kept in memory regardless of
# flush/drop state, snapshotted into obs/flight/<proc>.json on fatal
# paths (tpuflow.obs.flight). Small on purpose — forensics, not history.
_DEFAULT_FLIGHT_RING = 256


class Recorder:
    """Buffered JSONL event writer for one process."""

    def __init__(
        self,
        directory: str,
        *,
        proc: int = 0,
        flush_interval: float = 0.5,
        max_buffered: int | None = None,
    ):
        self.directory = os.path.abspath(directory)
        self.proc = int(proc)
        # pid in the name: the HEAD runner and gang member 0 both occupy
        # logical slot 0 and may flush concurrently — distinct files make
        # every append single-writer (no torn lines to skip at merge).
        self.path = os.path.join(
            self.directory, f"events.p{self.proc:05d}.{os.getpid()}.jsonl"
        )
        os.makedirs(self.directory, exist_ok=True)
        self._buf: list[dict] = []
        if max_buffered is None:
            try:
                max_buffered = int(
                    knobs.raw("TPUFLOW_OBS_MAX_BUFFERED", "")
                    or _DEFAULT_MAX_BUFFERED
                )
            except ValueError:
                max_buffered = _DEFAULT_MAX_BUFFERED
        self._max_buffered = max(1, max_buffered)
        self.dropped = 0  # events lost to overflow or failed flushes
        # Launch attempt (gang members only): stamped into every event so
        # the goodput ledger can stitch requeued attempts into one run.
        self.attempt: int | None = None
        env_attempt = knobs.raw("TPUFLOW_ATTEMPT")
        if env_attempt:
            try:
                self.attempt = int(env_attempt)
            except ValueError:
                pass
        try:
            ring = int(
                knobs.raw("TPUFLOW_OBS_FLIGHT_RING", "")
                or _DEFAULT_FLIGHT_RING
            )
        except ValueError:
            ring = _DEFAULT_FLIGHT_RING
        self._ring: collections.deque = collections.deque(
            maxlen=max(0, ring)
        )
        self._lock = threading.Lock()
        self._closed = False
        self._flush_interval = flush_interval
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="tpuflow-obs-flush"
        )
        self._thread.start()

    # ------------------------------------------------------------- record
    def record(self, kind: str, name: str, *, ts: float | None = None, **attrs) -> None:
        ev = {
            "kind": kind,
            "name": name,
            "ts": time.time() if ts is None else ts,
            "proc": self.proc,
            "pid": os.getpid(),
            **attrs,
        }
        if self.attempt is not None:
            ev.setdefault("launch", self.attempt)
        with self._lock:
            if self._closed:
                return
            # The flight ring sees every event — including ones the
            # bounded buffer is about to drop: the newest events are
            # exactly what a post-mortem needs.
            self._ring.append(ev)
            if len(self._buf) >= self._max_buffered:
                # Telemetry must never fail (or bloat) the run: beyond the
                # cap events are counted and dropped, surfaced at close.
                self.dropped += 1
                return
            self._buf.append(ev)

    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs)

    def _ring_snapshot(
        self, timeout: float = 0.25
    ) -> tuple[list[dict], bool]:
        """Copy of the flight ring, signal-handler safe: tries the buffer
        lock with a timeout (the interrupted frame may hold it) and falls
        back to a best-effort lockless copy. Returns ``(events,
        lock_was_free)`` — when the lock could not be acquired, callers
        must not touch any locked recorder API (a signal handler doing so
        would deadlock against the frame it interrupted)."""
        got = self._lock.acquire(timeout=timeout)
        try:
            try:
                return list(self._ring), got
            except RuntimeError:  # mutated during the lockless iteration
                return [], got
        finally:
            if got:
                self._lock.release()

    # -------------------------------------------------------------- flush
    def _drain(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        lines = "".join(
            json.dumps(ev, default=_jsonable) + "\n" for ev in buf
        )
        try:
            with open(self.path, "a") as f:
                f.write(lines)
        except OSError:
            # Telemetry must never fail the run — but the drained batch
            # is gone; count it so close() can surface the loss.
            with self._lock:
                self.dropped += len(buf)

    def _flush_loop(self) -> None:
        while not self._closed:
            self._wake.wait(self._flush_interval)
            self._wake.clear()
            self._drain()

    def flush(self) -> None:
        self._drain()

    def close(self) -> None:
        if self._closed:  # idempotent (configure() then atexit)
            return
        self._closed = True
        self._wake.set()
        self._drain()
        if self.dropped:
            # Final accounting event, appended directly (the buffer is
            # closed): a consumer summing ``obs.dropped`` values knows
            # exactly how many events this process lost. Best-effort —
            # a still-broken disk loses the accounting line too.
            try:
                with open(self.path, "a") as f:
                    f.write(
                        json.dumps(
                            {
                                "kind": "event",
                                "name": "obs.dropped",
                                "ts": time.time(),
                                "proc": self.proc,
                                "pid": os.getpid(),
                                "value": self.dropped,
                            }
                        )
                        + "\n"
                    )
            except OSError:
                pass
        self._thread.join(timeout=2)


def _jsonable(v: Any):
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


# ----------------------------------------------------------- module API
def configure(
    directory: str | None, *, proc: int | None = None
) -> Recorder | None:
    """(Re)point the process recorder at ``directory``; ``None`` disables.

    The flow runner calls this at run start with ``<run_dir>/obs`` and at
    run end with ``None``; gang member processes pick the directory up
    from ``TPUFLOW_OBS_DIR`` automatically (see ``_maybe_init_from_env``).
    """
    global _ENABLED, _RECORDER, _ENV_CHECKED
    with _LOCK:
        _ENV_CHECKED = True  # explicit configure overrides env discovery
        if _RECORDER is not None:
            _RECORDER.close()
            _RECORDER = None
        _ENABLED = False
        if directory is None:
            return None
        if proc is None:
            proc = int(
                knobs.raw("TPUFLOW_OBS_PROC")
                or knobs.raw("TPUFLOW_PROCESS_ID")
                or 0
            )
        _RECORDER = Recorder(directory, proc=proc)
        _ENABLED = True
        return _RECORDER


def _maybe_init_from_env() -> None:
    """One-time env discovery: a gang subprocess (or any process launched
    with TPUFLOW_OBS_DIR set) starts recording without explicit wiring."""
    global _ENV_CHECKED
    if _ENV_CHECKED:
        return
    with _LOCK:
        if _ENV_CHECKED:
            return
        _ENV_CHECKED = True
    d = knobs.raw("TPUFLOW_OBS_DIR")
    if d:
        configure(d)


def enabled() -> bool:
    if not _ENV_CHECKED:
        _maybe_init_from_env()
    return _ENABLED


def recorder() -> Recorder | None:
    return _RECORDER if enabled() else None


def span(name: str, **attrs):
    """Timed region context manager; a shared no-op when disabled."""
    if not _ENABLED:
        if _ENV_CHECKED:
            return _NOOP_SPAN
        _maybe_init_from_env()
        if not _ENABLED:
            return _NOOP_SPAN
    return _RECORDER.span(name, **attrs)


def counter(name: str, value: float = 1, **attrs) -> None:
    if _ENABLED or enabled():
        _RECORDER.record("counter", name, value=value, **attrs)


def gauge(name: str, value: float, **attrs) -> None:
    if _ENABLED or enabled():
        _RECORDER.record("gauge", name, value=value, **attrs)


def histogram(name: str, value: float, **attrs) -> None:
    if _ENABLED or enabled():
        _RECORDER.record("histogram", name, value=value, **attrs)


def event(name: str, **attrs) -> None:
    if _ENABLED or enabled():
        _RECORDER.record("event", name, **attrs)


def flush() -> None:
    if _RECORDER is not None:
        _RECORDER.flush()


def timed_iter(iterable, name: str):
    """Yield from ``iterable``, recording the consumer-visible wait for
    each item as a ``histogram`` observation of ``name``. When telemetry
    is disabled this returns the original iterable untouched — zero
    wrapper frames on the hot path."""
    if not enabled():
        return iterable

    def _gen():
        it = iter(iterable)
        while True:
            t0 = time.monotonic()
            try:
                item = next(it)
            except StopIteration:
                return
            histogram(name, time.monotonic() - t0)
            yield item

    return _gen()


@atexit.register
def _atexit_close() -> None:
    if _RECORDER is not None:
        try:
            _RECORDER.close()
        except Exception:
            pass
