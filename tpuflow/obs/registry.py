"""Run registry + cross-run regression ledger (ISSUE 16).

Every training run, serving run, and ``bench.py`` invocation appends ONE
schema-versioned headline record to the ``TPUFLOW_REGISTRY_PATH`` JSONL
— goodput fraction, tokens/s, TTFT/ITL percentiles (from the mergeable
buckets when the snapshot carries them), ``hbm_peak_frac``, the bench
digest keys the exit-3/4/5/6 gates read, git commit + dirty flag, and
platform provenance. The sensors existed (PRs 13–15); this file is the
memory that lets anything *compare* them: five rounds of BENCH history
become queryable the moment the one-shot importer backfills
BENCH_r01–r05.

Durability contract:

- **Atomic append.** One ``os.write`` of one full line on an
  ``O_APPEND`` fd — concurrent writers interleave whole lines, never
  characters (POSIX pipe-buf-sized appends), and a crash mid-append
  leaves at most one torn final line.
- **Torn-line-tolerant reads.** ``read_registry`` skips any line that
  does not parse (or lacks the record shape) instead of raising — a
  registry survives the crash that tore it.
- **Tolerant metric extraction.** Legacy records predate the PR 15 keys
  (``hbm_peak_frac``, ``programs_ledger``, ``fleet_snapshot_path``);
  every extractor here degrades to "metric absent", never ``KeyError``
  — the r01–r04 backfill exercises exactly that.

Regression math is the PR 15 detector idiom reused host-side: the last
value vs the trailing window's **median + MAD** (``TPUFLOW_REGISTRY_
WINDOW`` / ``TPUFLOW_REGISTRY_ZMADS``), so one jittery round does not
read as a regression and a real cliff does. ``python -m tpuflow.obs
trend`` / ``compare`` render it jax-free; ``bench.py`` renders the
"vs last 5 runs" verdict table from the same rows.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Iterable

from tpuflow.obs import recorder as _rec
from tpuflow.utils import knobs

SCHEMA = 1

# Registry filename bench.py defaults to (beside its BENCH_r*.json
# records) when TPUFLOW_REGISTRY_PATH is unset.
DEFAULT_BASENAME = "TPU_REGISTRY.jsonl"

# (metric name, path into the bench compact-summary digest). Every
# lookup is guarded — a legacy digest missing a path yields an absent
# metric, never a KeyError (the r01–r04 backfill hits this on the
# post-PR-15 keys: hbm_peak_frac, programs_ledger, fleet snapshots).
_DIGEST_PATHS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("host_combined_gbps", ("host_combined_gbps",)),
    ("disk_combined_gbps", ("disk_combined_gbps",)),
    ("train_mfu", ("train", "mfu")),
    ("train_tokens_per_s", ("train", "tokens_per_s")),
    ("best_mfu_sweep", ("best_mfu_sweep",)),
    # exit-3 gate inputs
    ("spec_decode_numerics_ok", ("spec_decode", "numerics_ok")),
    ("spec_decode_speedup", ("spec_decode", "speedup")),
    ("serve_tokens_per_s", ("serving", "tokens_per_s")),
    ("serve_vs_sequential", ("serving", "vs_sequential")),
    ("serve_ttft_p99_s", ("serving", "ttft_p99_s")),
    ("serve_itl_p99_s", ("serving", "itl_p99_s")),
    ("hbm_peak_frac", ("serving", "hbm_peak_frac")),
    # exit-6 gate input
    ("paged_vs_slot", ("serving_paged", "vs_slot")),
    ("paged_tokens_per_s", ("serving_paged", "tokens_per_s")),
    # ISSUE 18: front-door router headline (absent on pre-router
    # records — trend/compare degrade to "metric absent" by the same
    # guarded walk as every other path here). router_dropped must be 0.
    ("router_requests", ("serving_router", "router_requests")),
    ("router_reroutes", ("serving_router", "router_reroutes")),
    ("router_dropped", ("serving_router", "router_dropped")),
    # exit-4 gate inputs
    ("int8_weight_only_speedup", ("int8_weight_only", "speedup")),
    ("int8_fused_native_speedup", ("int8_fused_native", "speedup")),
    # exit-5 gate inputs
    ("flash_crossover_T", ("flash_crossover_T",)),
    ("flash_fused_vs_split_T2048", ("flash_fused_vs_split_T2048",)),
    ("flash_fwdbwd_auto_T512", ("flash_fwdbwd_auto_T512",)),
    ("exposed_comm_s", ("exposed_comm_s",)),
)

# Metrics where DOWN is the good direction; everything else is
# higher-is-better (throughputs, speedups, fractions-of-peak).
_LOWER_IS_BETTER_TOKENS = (
    "ttft", "itl", "exposed_comm", "hbm_peak", "hbm_used", "slo_",
    "compile_s", "dropped", "reroute",
)


def lower_is_better(metric: str) -> bool:
    return any(tok in metric for tok in _LOWER_IS_BETTER_TOKENS)


# -------------------------------------------------------------- records
def registry_path(default: str | None = None) -> str | None:
    """The armed registry file, or ``default`` when the knob is unset
    (None disables the implicit run-end appends)."""
    return knobs.raw("TPUFLOW_REGISTRY_PATH") or default


def git_stamp(repo: str | None = None) -> tuple[str | None, bool | None]:
    """(commit, dirty) for ``repo`` (default: this package's checkout);
    (None, None) when git is unavailable — provenance is best-effort."""
    if repo is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo,
            capture_output=True, text=True, timeout=5,
        ).stdout.strip() or None
        if commit is None:
            return None, None
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=repo,
            capture_output=True, text=True, timeout=5,
        ).stdout.strip())
        return commit, dirty
    except (OSError, subprocess.SubprocessError):
        return None, None


def make_record(
    kind: str,
    metrics: dict[str, float],
    *,
    source: str,
    run_id: str | None = None,
    platform: str | None = None,
    git: str | None = None,
    git_dirty: bool | None = None,
    ts: float | None = None,
) -> dict[str, Any]:
    if ts is None:
        ts = time.time()
    if run_id is None:
        run_id = f"{kind}-{int(ts)}-{os.getpid()}"
    rec: dict[str, Any] = {
        "schema": SCHEMA,
        "run_id": run_id,
        "ts": round(float(ts), 3),
        "kind": kind,
        "source": source,
        "metrics": dict(metrics),
    }
    if platform is not None:
        rec["platform"] = platform
    if git is not None:
        rec["git"] = git
    if git_dirty is not None:
        rec["git_dirty"] = git_dirty
    return rec


def append_record(path: str, record: dict) -> bool:
    """Crash-safe single-line append: the whole line lands in ONE
    O_APPEND write, so concurrent appenders interleave records, not
    bytes, and a crash tears at most the final line (which
    ``read_registry`` skips). Failures return False, never raise."""
    try:
        data = (json.dumps(record, sort_keys=True, default=str) + "\n").encode()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
    except OSError:
        return False
    _rec.event(
        "registry.append",
        path=path,
        run_id=record.get("run_id"),
        run_kind=record.get("kind"),
        metrics=len(record.get("metrics") or ()),
    )
    return True


def read_registry(path: str) -> list[dict]:
    """Every well-formed record in file order. A torn final line (crash
    mid-append), a corrupt line, or a non-record JSON value is skipped —
    reading a damaged registry never raises."""
    out: list[dict] = []
    try:
        f = open(path, encoding="utf-8", errors="replace")
    except OSError:
        return out
    with f:
        for line in f:
            if not line.endswith("\n"):
                continue  # torn tail: the append died mid-write
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and isinstance(
                rec.get("metrics"), dict
            ):
                out.append(rec)
    return out


# ------------------------------------------------- metric extraction
def _num(v: Any) -> float | None:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)) and v == v:  # NaN-free
        return float(v)
    return None


def _walk(d: Any, path: tuple[str, ...]) -> Any:
    for key in path:
        if not isinstance(d, dict):
            return None
        d = d.get(key)
    return d


def digest_metrics(digest: dict | None) -> dict[str, float]:
    """Flat metrics from a bench compact-summary digest. Tolerant by
    construction: every path is walked with .get, a missing or
    non-numeric leaf is simply absent from the result."""
    out: dict[str, float] = {}
    if not isinstance(digest, dict):
        return out
    for name, path in _DIGEST_PATHS:
        v = _num(_walk(digest, path))
        if v is not None:
            out[name] = v
    return out


def bench_metrics(parsed: dict | None) -> tuple[dict[str, float], dict]:
    """(metrics, provenance) from any generation of bench record:
    r01's bare metric/value, r02–r03's full-record ``extra`` shape,
    r05's compact ``summary`` digest. Absent keys degrade to absent
    metrics — never KeyError (the backfill's legacy records miss every
    post-PR-15 key)."""
    out: dict[str, float] = {}
    prov: dict[str, Any] = {}
    if not isinstance(parsed, dict):
        return out, prov
    v = _num(parsed.get("value"))
    if v is not None:
        out["host_combined_gbps"] = v
    v = _num(parsed.get("vs_baseline"))
    if v is not None:
        out["vs_baseline"] = v
    summary = parsed.get("summary")
    if isinstance(summary, dict):
        out.update(digest_metrics(summary))
        plat = _walk(summary, ("train", "platform"))
        if isinstance(plat, str):
            prov["platform"] = plat
        if isinstance(summary.get("git"), str):
            prov["git"] = summary["git"]
        return out, prov
    extra = parsed.get("extra")
    if isinstance(extra, dict):
        v = _num(_walk(extra, ("tiers", "disk", "combined_gbps")))
        if v is not None:
            out["disk_combined_gbps"] = v
        train = extra.get("train")
        if isinstance(train, dict):
            for name, key in (
                ("train_mfu", "mfu"),
                ("train_tokens_per_s", "tokens_per_s"),
            ):
                v = _num(train.get(key))
                if v is not None:
                    out[name] = v
            if isinstance(train.get("platform"), str):
                prov["platform"] = train["platform"]
    return out, prov


def snapshot_metrics(snap: dict) -> dict[str, float]:
    """Headline metrics from a live goodput/serve ``/status`` snapshot.
    TTFT/ITL percentiles come from the mergeable histogram buckets when
    the snapshot carries them (the fleet-exact source), falling back to
    the pre-aggregated gauges."""
    out: dict[str, float] = {}
    for key in (
        "goodput_fraction", "tokens_per_s", "mfu", "step_rate",
        "hbm_peak_frac", "hbm_used_frac", "serve_tokens_per_s",
        "serve_requests", "serve_slo_violations", "serve_queue_depth",
        "nonfinite_steps",
        # ISSUE 18: router headline keys (present only when the
        # snapshot IS a front-door /status — legacy and replica
        # snapshots simply lack them).
        "router_requests", "router_reroutes", "router_dropped",
    ):
        v = _num(snap.get(key))
        if v is not None:
            out[key] = v
    from tpuflow.obs import fleet as _fleet

    for which in ("ttft", "itl"):
        h = snap.get(f"serve_{which}_hist")
        p = _fleet.hist_percentiles(h) if isinstance(h, dict) else None
        for q in ("p50", "p95", "p99"):
            v = _num(p.get(q)) if p else _num(
                snap.get(f"serve_{which}_{q}_s")
            )
            if v is not None:
                out[f"serve_{which}_{q}_s"] = v
    return out


def maybe_append_live(kind: str, snap: dict | None = None) -> bool:
    """Run-end hook (gang train legs, serve_forever): append this
    process's headline to the registry IF ``TPUFLOW_REGISTRY_PATH`` is
    armed — a single knob read when it is not. Never raises."""
    path = registry_path()
    if not path:
        return False
    try:
        if snap is None:
            from tpuflow.obs import goodput as _goodput

            snap = _goodput.live().snapshot()
        metrics = snapshot_metrics(snap)
        commit, dirty = git_stamp()
        platform = "tpu" if "hbm_limit_bytes" in snap else None
        rec = make_record(
            kind, metrics, source=f"{kind}:live", platform=platform,
            git=commit, git_dirty=dirty,
        )
        return append_record(path, rec)
    except Exception:
        return False


# ------------------------------------------------------------- backfill
def record_from_bench_file(path: str) -> dict | None:
    """One registry record from a BENCH_r*.json driver capture; None
    when the file is unreadable. A record whose ``parsed`` is null
    (r04's truncated tail) still imports — with whatever the tail's
    last complete JSON line yields, possibly no metrics at all."""
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(raw, dict):
        return None
    parsed = raw.get("parsed")
    if not isinstance(parsed, dict):
        # r04: the driver's 2000-char tail truncated the record and
        # parsed landed null. Salvage the last complete JSON line.
        tail = raw.get("tail")
        parsed = None
        if isinstance(tail, str):
            for line in reversed(tail.splitlines()):
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    try:
                        cand = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(cand, dict):
                        parsed = cand
                        break
    metrics, prov = bench_metrics(parsed)
    base = os.path.basename(path)
    stem = base.rsplit(".", 1)[0]
    n = raw.get("n")
    return make_record(
        "bench",
        metrics,
        source=f"backfill:{base}",
        run_id=stem,
        platform=prov.get("platform"),
        git=prov.get("git"),
        ts=float(n) if isinstance(n, (int, float)) else 0.0,
    )


def backfill_bench(bench_dir: str, path: str) -> int:
    """One-shot importer: append a record per BENCH_r*.json under
    ``bench_dir`` that the registry does not already hold (idempotent —
    rerunning imports nothing). Returns the number appended."""
    try:
        names = sorted(
            n for n in os.listdir(bench_dir)
            if n.startswith("BENCH_r") and n.endswith(".json")
        )
    except OSError:
        return 0
    seen = {r.get("run_id") for r in read_registry(path)}
    appended = 0
    for name in names:
        rec = record_from_bench_file(os.path.join(bench_dir, name))
        if rec is None or rec["run_id"] in seen:
            continue
        if append_record(path, rec):
            appended += 1
            seen.add(rec["run_id"])
    return appended


# ---------------------------------------------------------- trend math
def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(vals: list[float], med: float) -> float:
    return _median([abs(v - med) for v in vals])


def metric_series(
    records: Iterable[dict],
) -> dict[str, list[tuple[str, float]]]:
    """metric -> [(run_id, value)] in record order."""
    series: dict[str, list[tuple[str, float]]] = {}
    for rec in records:
        rid = str(rec.get("run_id", "?"))
        for m, v in (rec.get("metrics") or {}).items():
            fv = _num(v)
            if fv is not None:
                series.setdefault(m, []).append((rid, fv))
    return series


def verdict_rows(
    history: list[dict],
    current: dict[str, float],
    *,
    window: int | None = None,
    zmads: float | None = None,
) -> list[dict]:
    """Per-metric verdicts for ``current`` against the trailing window
    of ``history`` records — the PR 15 median+MAD spike detector reused
    host-side. A metric with no history is "new"; a last-vs-median
    deviation inside ``zmads`` robust deviations (with a 1% jitter
    floor, so an all-identical window does not make any change
    infinitely significant) is "ok"; outside it, the metric's
    good-direction decides "improved" vs "REGRESSED"."""
    if window is None:
        window = knobs.get_int("TPUFLOW_REGISTRY_WINDOW")
    if zmads is None:
        zmads = knobs.get_float("TPUFLOW_REGISTRY_ZMADS")
    series = metric_series(history)
    rows: list[dict] = []
    for metric in sorted(set(series) | set(current)):
        cur = _num(current.get(metric))
        past = [v for _, v in series.get(metric, [])][-window:]
        row: dict[str, Any] = {
            "metric": metric,
            "n": len(past),
            "last": cur,
        }
        if cur is None:
            row["verdict"] = "absent"
        elif not past:
            row["verdict"] = "new"
        else:
            med = _median(past)
            mad = _mad(past, med)
            delta = cur - med
            # 1.4826*MAD ~ sigma for normal jitter; the max() floor
            # keeps a constant history (MAD 0) from flagging noise.
            scale = max(1.4826 * mad, 0.01 * abs(med), 1e-12)
            z = delta / scale
            row.update(
                median=round(med, 6), mad=round(mad, 6),
                delta=round(delta, 6), z=round(z, 2),
            )
            if abs(z) <= zmads:
                row["verdict"] = "ok"
            else:
                good_down = lower_is_better(metric)
                improved = delta < 0 if good_down else delta > 0
                row["verdict"] = "improved" if improved else "REGRESSED"
        rows.append(row)
    return rows


def trend_rows(
    records: list[dict],
    *,
    metrics: list[str] | None = None,
    window: int | None = None,
    zmads: float | None = None,
) -> list[dict]:
    """The registry's newest record judged against its own trailing
    window (``obs trend``). ``metrics`` filters the rows."""
    if not records:
        return []
    rows = verdict_rows(
        records[:-1],
        dict(records[-1].get("metrics") or {}),
        window=window,
        zmads=zmads,
    )
    if metrics:
        keep = set(metrics)
        rows = [r for r in rows if r["metric"] in keep]
    return rows


def compare_rows(rec_a: dict, rec_b: dict) -> list[dict]:
    """Per-metric A→B deltas over the union of both records' metrics; a
    side missing the metric reads "absent" (legacy records, by design)."""
    ma = rec_a.get("metrics") or {}
    mb = rec_b.get("metrics") or {}
    rows: list[dict] = []
    for metric in sorted(set(ma) | set(mb)):
        a, b = _num(ma.get(metric)), _num(mb.get(metric))
        row: dict[str, Any] = {"metric": metric, "a": a, "b": b}
        if a is None or b is None:
            row["verdict"] = "absent"
        else:
            row["delta"] = round(b - a, 6)
            if a != 0:
                row["delta_pct"] = round(100.0 * (b - a) / abs(a), 2)
            if b == a:
                row["verdict"] = "same"
            else:
                good_down = lower_is_better(metric)
                improved = b < a if good_down else b > a
                row["verdict"] = "improved" if improved else "REGRESSED"
        rows.append(row)
    return rows


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def format_rows(rows: list[dict], columns: tuple[str, ...]) -> str:
    """Aligned text table (the CLI / bench verdict rendering)."""
    headers = columns
    body = [[_fmt(r.get(c)) for c in headers] for r in rows]
    widths = [
        max(len(h), *(len(b[i]) for b in body)) if body else len(h)
        for i, h in enumerate(headers)
    ]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for b in body:
        lines.append("  ".join(c.ljust(w) for c, w in zip(b, widths)))
    return "\n".join(lines)


def bench_append_and_verdict(
    compact: dict, repo: str, log=print
) -> list[dict]:
    """bench.py's registry hook: append this invocation's digest to the
    registry (knob path, else ``TPU_REGISTRY.jsonl`` beside the bench
    records) and render the auto "vs last N runs" verdict table from
    the trailing history. Returns the verdict rows."""
    path = registry_path(os.path.join(repo, DEFAULT_BASENAME))
    history = read_registry(path)
    metrics, prov = bench_metrics(compact)
    commit, dirty = git_stamp(repo)
    rec = make_record(
        "bench",
        metrics,
        source="bench.py",
        platform=prov.get("platform"),
        git=commit,
        git_dirty=dirty,
    )
    append_record(path, rec)
    window = knobs.get_int("TPUFLOW_REGISTRY_WINDOW")
    rows = verdict_rows(history, metrics)
    judged = [r for r in rows if r["verdict"] not in ("absent",)]
    if judged:
        log(f"[bench] vs last {min(len(history), window)} runs ({path}):")
        for line in format_rows(
            judged, ("metric", "last", "median", "delta", "z", "verdict")
        ).splitlines():
            log(f"[bench]   {line}")
        regressed = [r["metric"] for r in judged
                     if r["verdict"] == "REGRESSED"]
        if regressed:
            log(
                "[bench] REGRESSED vs trailing median+MAD: "
                + ", ".join(regressed)
            )
    return rows
