"""Serving observatory (ISSUE 13): engine-time ledger, SLO accounting,
and the per-request access log — the serving analog of ``goodput.py``.

PR 6 gave training a ledger that charges every second of wall time to
exactly one bucket; serving (the repo's largest subsystem after the
continuous-batching, int8, and paged-KV PRs) exported only aggregate
gauges. This module closes that gap with three host-side pieces, none
of which touch a jitted program (the never-recompile contract stays
intact — all instrumentation is data *about* the schedule, never data
*in* it):

- :class:`ServeLedger` — the engine-time ledger. A monotonic cursor
  sweeps forward: every charged span advances it, every gap between
  charges lands in ``host_sched``, and the idle sleep in
  ``serve_forever`` charges ``idle`` — so the buckets
  (``prefill``/``decode``/``verify``/``insert``/``host_sched``/``idle``)
  sum to the measured wall **by construction**, exactly like the
  training ledger's interval sweep. It also folds in the
  token-efficiency gauges the scheduler already knows: occupancy-
  weighted decode utilization (live rows / batch rows per block),
  masked-row waste from the (fp,int8)x(spec,plain) group partition, and
  speculative draft tokens wasted vs accepted — plus the declared-SLO
  counters (``TPUFLOW_SERVE_SLO_TTFT_MS`` / ``TPUFLOW_SERVE_SLO_ITL_MS``).

- :class:`AccessLog` — one JSONL line per terminal request
  (``access.p<proc>.<pid>.jsonl`` beside the event fragments under the
  run's ``obs/`` dir), carrying the request's whole lifecycle: TTFT,
  the per-tick ITL observations, finish reason, pages/prefix stats, and
  the trace. Appends are line-buffered so a mid-run reader always sees
  whole records.

- :func:`load_access_log` / :func:`summarize_access` — the reader side:
  ``python -m tpuflow.obs serve-summary <run_dir>`` reproduces the
  /metrics TTFT/ITL percentiles from the access log alone (same
  :func:`pctl` math as the live exporter), split by numeric path and
  spec/plain group. No jax import anywhere in this module — safe from a
  login shell against a live run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Iterable

from tpuflow.utils import knobs

# Engine-time buckets, in display order. ``verify`` is the speculative
# twin of ``decode``; ``insert`` is the jitted admission write;
# ``host_sched`` is everything the host does between device dispatches
# (queue pops, drafts, token harvest, telemetry); ``idle`` is the
# serve_forever sleep when there was nothing to do.
SERVE_BUCKETS: tuple[str, ...] = (
    "prefill", "decode", "verify", "insert", "host_sched", "idle",
)

# Traffic groups: the (numeric path) x (spec/plain) partition the
# scheduler already runs decode blocks by.
GROUPS: tuple[str, ...] = (
    "fp.plain", "fp.spec", "int8.plain", "int8.spec",
)


def group_key(quantize: bool, speculative: bool) -> str:
    """The request's traffic-group label, matching the scheduler's
    (quant, spec) decode-block partition."""
    return ("int8" if quantize else "fp") + (
        ".spec" if speculative else ".plain"
    )


def pctl(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list — THE percentile
    used by the live exporter, the access-log summary, and the bench
    digest, so ``serve-summary`` reproduces /metrics exactly."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def percentiles(vals: Iterable[float]) -> dict[str, float] | None:
    """{count, p50, p95, p99, max} over raw observations (None when
    empty) — one shape for every latency table this module emits."""
    vs = sorted(float(v) for v in vals)
    if not vs:
        return None
    return {
        "count": len(vs),
        "p50": pctl(vs, 0.50),
        "p95": pctl(vs, 0.95),
        "p99": pctl(vs, 0.99),
        "max": vs[-1],
    }


def resolve_slo_s(name: str) -> float | None:
    """Declared SLO knob in SECONDS (the knobs are milliseconds —
    operator dashboards speak ms, the ledger compares monotonic
    seconds). Unset/malformed/non-positive → None (SLO accounting off
    for that dimension; a typo must not flood violations)."""
    # tpulint: disable=knob-dynamic -- name is forwarded verbatim from
    # literal call sites, which the string-literal declaration rule
    # still validates; knobs refuses undeclared names at runtime.
    v = knobs.get_float_lenient(name)
    if v is None or v <= 0:
        return None
    return float(v) / 1000.0


# Bounded per-group latency reservoirs: enough for stable p99 without
# letting a week-long server grow without bound.
_RESERVOIR = 4096


class ServeLedger:
    """Host-side engine-time accounting for one ServeEngine.

    Cursor discipline: ``bucket(name)`` charges the context-managed span
    to ``name`` and the gap since the previous charge to ``host_sched``;
    ``snapshot()`` settles the trailing gap the same way — so
    ``sum(buckets) == wall`` holds at every snapshot, by construction
    (the acceptance criterion's 5% slack only absorbs the float
    rounding of the report itself). Pure python; ~1µs per charge."""

    def __init__(
        self,
        slo_ttft_s: float | None = None,
        slo_itl_s: float | None = None,
    ):
        self.slo_ttft_s = slo_ttft_s
        self.slo_itl_s = slo_itl_s
        self.reset()

    def reset(self) -> None:
        """Restart the accounting window (bench drives reset before the
        timed window so warmup/compile lands outside it)."""
        now = time.monotonic()
        self._t0 = now
        self._cursor = now
        self.buckets: dict[str, float] = {b: 0.0 for b in SERVE_BUCKETS}
        # Decode-block efficiency accumulators (rows are slot-rows of
        # the persistent program's fixed batch).
        self._block_rows = 0       # batch rows dispatched, all blocks
        self._block_live = 0       # rows live in the dispatching group
        self._block_masked = 0     # rows live overall but masked out
        # Speculative economics.
        self.spec_drafted = 0      # draft tokens sent to verify blocks
        self.spec_accepted = 0     # draft tokens the model agreed with
        # SLO accounting (per-group split feeds the fleet SLO rates,
        # ISSUE 14).
        self.slo_violations = 0
        self.slo_ttft_violations = 0
        self.slo_itl_violations = 0
        self.slo_by_group: dict[str, int] = {}
        # Per-group latency reservoirs.
        self._ttft: dict[str, list[float]] = {}
        self._itl: dict[str, list[float]] = {}

    # ----------------------------------------------------------- charging
    def bucket(self, name: str) -> "_Charge":
        """Context manager charging its span to ``name`` (the preceding
        uncharged gap goes to host_sched)."""
        return _Charge(self, name)

    def _charge(self, name: str, start: float, end: float) -> None:
        if start > self._cursor:
            self.buckets["host_sched"] += start - self._cursor
        self.buckets[name] += max(end - start, 0.0)
        self._cursor = max(end, self._cursor)

    # ------------------------------------------------------- block notes
    def note_decode_block(
        self,
        batch_rows: int,
        group_live: int,
        total_live: int,
        *,
        spec: bool = False,
        drafted: int = 0,
        committed: int = 0,
    ) -> None:
        """One group's decode/verify dispatch: ``batch_rows`` is the
        program's fixed batch, ``group_live`` the rows live in THIS
        group, ``total_live`` the rows live engine-wide — the difference
        is the masked-row waste the group partition pays. Speculative
        blocks also report drafted tokens vs committed (committed
        includes one bonus token per live row, so accepted drafts =
        committed - group_live, floored at 0)."""
        self._block_rows += int(batch_rows)
        self._block_live += int(group_live)
        self._block_masked += max(int(total_live) - int(group_live), 0)
        if spec:
            self.spec_drafted += int(drafted)
            self.spec_accepted += max(int(committed) - int(group_live), 0)

    @property
    def decode_utilization(self) -> float | None:
        """Occupancy-weighted decode utilization: live rows / batch rows
        summed over every dispatched block (1.0 = every row of every
        block earned its FLOPs)."""
        if not self._block_rows:
            return None
        return self._block_live / self._block_rows

    @property
    def masked_row_waste(self) -> float | None:
        """Fraction of dispatched batch rows that were live engine-wide
        but masked OUT of the dispatching group's program — the price of
        the (fp,int8)x(spec,plain) partition on mixed traffic."""
        if not self._block_rows:
            return None
        return self._block_masked / self._block_rows

    @property
    def spec_wasted(self) -> int:
        """Draft tokens the verify forward computed and threw away."""
        return max(self.spec_drafted - self.spec_accepted, 0)

    # ------------------------------------------------------ latency + SLO
    def note_ttft(self, group: str, ttft_s: float) -> None:
        r = self._ttft.setdefault(group, [])
        if len(r) < _RESERVOIR:
            r.append(float(ttft_s))

    def note_itl(self, group: str, itl_s: float) -> None:
        r = self._itl.setdefault(group, [])
        if len(r) < _RESERVOIR:
            r.append(float(itl_s))

    def _count_group(self, group: str | None) -> None:
        if group:
            self.slo_by_group[group] = self.slo_by_group.get(group, 0) + 1

    def check_ttft(
        self, ttft_s: float | None, group: str | None = None
    ) -> bool:
        """True (and counted, split by traffic group when given) when
        the declared TTFT SLO is violated."""
        if self.slo_ttft_s is None or ttft_s is None:
            return False
        if ttft_s > self.slo_ttft_s:
            self.slo_violations += 1
            self.slo_ttft_violations += 1
            self._count_group(group)
            return True
        return False

    def check_itl(
        self, itl_s: float | None, group: str | None = None
    ) -> bool:
        """True (and counted, split by traffic group when given) when
        one decode tick's per-token latency violated the declared ITL
        SLO."""
        if self.slo_itl_s is None or itl_s is None:
            return False
        if itl_s > self.slo_itl_s:
            self.slo_violations += 1
            self.slo_itl_violations += 1
            self._count_group(group)
            return True
        return False

    # ----------------------------------------------------------- reports
    def wall_s(self) -> float:
        return time.monotonic() - self._t0

    def fractions(self) -> dict[str, float]:
        """Bucket fractions of wall-so-far; the pending (uncharged) tail
        counts as host_sched, without mutating the ledger."""
        now = time.monotonic()
        wall = max(now - self._t0, 1e-9)
        out = {}
        for b in SERVE_BUCKETS:
            v = self.buckets[b]
            if b == "host_sched":
                v += max(now - self._cursor, 0.0)
            out[b] = v / wall
        return out

    def snapshot(self) -> dict[str, Any]:
        """Settled view: buckets (summing to wall), fractions, the
        efficiency gauges, per-group and aggregate TTFT/ITL
        percentiles, and the SLO counters."""
        now = time.monotonic()
        if now > self._cursor:  # settle the tail into host_sched
            self.buckets["host_sched"] += now - self._cursor
            self._cursor = now
        wall = max(now - self._t0, 1e-9)
        all_ttft = [v for r in self._ttft.values() for v in r]
        all_itl = [v for r in self._itl.values() for v in r]
        out: dict[str, Any] = {
            "wall_s": wall,
            "buckets": dict(self.buckets),
            "fractions": {
                b: self.buckets[b] / wall for b in SERVE_BUCKETS
            },
            "decode_utilization": self.decode_utilization,
            "masked_row_waste": self.masked_row_waste,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_wasted": self.spec_wasted,
            "slo_violations": self.slo_violations,
            "slo_ttft_violations": self.slo_ttft_violations,
            "slo_itl_violations": self.slo_itl_violations,
            "slo_by_group": dict(sorted(self.slo_by_group.items())),
            "ttft": {
                g: percentiles(r) for g, r in sorted(self._ttft.items())
            },
            "itl": {
                g: percentiles(r) for g, r in sorted(self._itl.items())
            },
        }
        agg_t = percentiles(all_ttft)
        agg_i = percentiles(all_itl)
        if agg_t:
            out["ttft_p50_s"] = agg_t["p50"]
            out["ttft_p99_s"] = agg_t["p99"]
        if agg_i:
            out["itl_p50_s"] = agg_i["p50"]
            out["itl_p99_s"] = agg_i["p99"]
        return out


class _Charge:
    __slots__ = ("_led", "_name", "_t0")

    def __init__(self, led: ServeLedger, name: str):
        if name not in led.buckets:
            raise KeyError(
                f"unknown serve-ledger bucket {name!r} "
                f"(want one of {SERVE_BUCKETS})"
            )
        self._led = led
        self._name = name

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._led._charge(self._name, self._t0, time.monotonic())
        return False


# ------------------------------------------------------------ access log
ACCESS_PREFIX = "access.p"


class AccessLog:
    """Per-request JSONL writer beside the event fragments.

    One line per TERMINAL request (complete or drained). Lines are
    written whole under a lock and flushed immediately — request
    completions are orders of magnitude rarer than token events, and a
    mid-run ``serve-summary`` must see every finished request. Write
    failures are counted, never raised (telemetry must not fail a
    server)."""

    def __init__(self, directory: str, *, proc: int = 0):
        self.directory = os.path.abspath(directory)
        self.proc = int(proc)
        self.path = os.path.join(
            self.directory,
            f"{ACCESS_PREFIX}{self.proc:05d}.{os.getpid()}.jsonl",
        )
        os.makedirs(self.directory, exist_ok=True)
        self.dropped = 0
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        line = json.dumps(record, default=str) + "\n"
        with self._lock:
            try:
                with open(self.path, "a") as f:
                    f.write(line)
            except OSError:
                self.dropped += 1


def access_paths(run_dir: str) -> list[str]:
    """Every per-process access-log fragment under ``<run_dir>/obs``
    (or under ``run_dir`` itself when pointed straight at an obs dir)."""
    out: list[str] = []
    for d in (os.path.join(run_dir, "obs"), run_dir):
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        out = [
            os.path.join(d, n)
            for n in names
            if n.startswith(ACCESS_PREFIX) and n.endswith(".jsonl")
        ]
        if out:
            return out
    return out


def load_access_log(run_dir: str) -> list[dict]:
    """All access records under the run dir, submit-time ordered.
    Torn tails (a live writer) are skipped, like the event reader."""
    records: list[dict] = []
    for path in access_paths(run_dir):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("request", 0)))
    return records


def summarize_access(records: Iterable[dict]) -> dict[str, Any]:
    """Fold access records into the serving summary: request/token
    counts, finish reasons, SLO violations, and TTFT/ITL percentiles —
    aggregate and split by traffic group — using the SAME ``pctl`` math
    as the live /metrics exporter, so the two surfaces agree on the
    numbers they share."""
    records = list(records)
    ttft_all: list[float] = []
    itl_all: list[float] = []
    by_group: dict[str, dict[str, list[float]]] = {}
    reasons: dict[str, int] = {}
    tokens = 0
    slo = 0
    for r in records:
        g = r.get("group") or group_key(
            bool(r.get("quant")), bool(r.get("spec"))
        )
        slot = by_group.setdefault(g, {"ttft": [], "itl": []})
        t = r.get("ttft_s")
        if isinstance(t, (int, float)):
            ttft_all.append(float(t))
            slot["ttft"].append(float(t))
        for v in r.get("itl_s") or ():
            if isinstance(v, (int, float)):
                itl_all.append(float(v))
                slot["itl"].append(float(v))
        reason = r.get("finish_reason") or "unknown"
        reasons[reason] = reasons.get(reason, 0) + 1
        tokens += int(r.get("tokens", 0) or 0)
        slo += int(r.get("slo_violations", 0) or 0)
    out: dict[str, Any] = {
        "requests": len(records),
        "tokens": tokens,
        "finish_reasons": dict(sorted(reasons.items())),
        "slo_violations": slo,
        "ttft": percentiles(ttft_all),
        "itl": percentiles(itl_all),
        "by_group": {
            g: {
                "requests": len(v["ttft"]),
                "ttft": percentiles(v["ttft"]),
                "itl": percentiles(v["itl"]),
            }
            for g, v in sorted(by_group.items())
        },
    }
    return out
