"""Run-level telemetry assembly: merge per-process event files, summarize.

``merge_run_events(run_dir)`` unions every gang worker's
``obs/events.p*.jsonl`` into one time-ordered ``events.jsonl`` at the run
root — the single artifact downstream flows and the timeline card read.
``summarize(events)`` folds the stream into headline metrics (step time,
tokens/s, checkpoint GB/s, loader wait) plus per-name aggregates.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable

MERGED_NAME = "events.jsonl"
OBS_SUBDIR = "obs"


def obs_dir(run_dir: str) -> str:
    return os.path.join(run_dir, OBS_SUBDIR)


def read_events(path: str) -> list[dict]:
    """Parse one JSONL event file, skipping unparsable lines (a crashed
    writer may leave a torn tail — telemetry reads must stay best-effort)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        return []
    return out


def merge_run_events(run_dir: str, *, out_name: str = MERGED_NAME) -> list[dict]:
    """Merge every per-process event file under ``<run_dir>/obs`` into one
    time-sorted ``<run_dir>/events.jsonl``; returns the merged events.

    Idempotent: re-running re-reads the fragments and rewrites the merged
    file (fragments are kept — they are the ground truth; the merge is a
    view). A run with no telemetry yields an empty list and no file."""
    d = obs_dir(run_dir)
    events: list[dict] = []
    try:
        names = sorted(
            n
            for n in os.listdir(d)
            if n.startswith("events.p") and n.endswith(".jsonl")
        )
    except OSError:
        return []
    for name in names:
        events.extend(read_events(os.path.join(d, name)))
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("proc", 0)))
    if events:
        out = os.path.join(run_dir, out_name)
        tmp = out + ".tmp"
        try:
            with open(tmp, "w") as f:
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
            os.replace(tmp, out)
        except OSError:
            pass
    return events


def load_run_events(run_dir: str) -> list[dict]:
    """The run's merged event stream: the committed ``events.jsonl`` if the
    runner already merged, else merged on the fly from the fragments."""
    path = os.path.join(run_dir, MERGED_NAME)
    if os.path.exists(path):
        return read_events(path)
    return merge_run_events(run_dir)


def _pctl(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def summarize(events: Iterable[dict]) -> dict[str, Any]:
    """Fold an event stream into per-name aggregates + headline metrics.

    Returns::

        {"spans": {name: {count, total_s, mean_s, max_s}},
         "counters": {name: total},
         "gauges": {name: {last, max}},
         "histograms": {name: {count, mean, p50, max, total}},
         "health": {...},     # anomalies/rollbacks/profiles/last numerics
         "goodput": {...},    # wall decomposed into labeled buckets
         "headline": {...}}   # step time, tokens/s, ckpt GB/s, data wait
    """
    events = list(events)
    spans: dict[str, dict] = {}
    counters: dict[str, float] = {}
    gauges: dict[str, dict] = {}
    hists: dict[str, list[float]] = {}
    ckpt_saves: list[dict] = []
    ckpt_restores: list[dict] = []
    for ev in events:
        kind, name = ev.get("kind"), ev.get("name")
        if kind == "span":
            s = spans.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            d = float(ev.get("dur_s", 0.0))
            s["count"] += 1
            s["total_s"] += d
            s["max_s"] = max(s["max_s"], d)
            if name == "ckpt.save":
                ckpt_saves.append(ev)
            elif name == "ckpt.restore":
                ckpt_restores.append(ev)
        elif kind == "counter":
            counters[name] = counters.get(name, 0.0) + float(
                ev.get("value", 1.0)
            )
        elif kind == "gauge":
            g = gauges.setdefault(name, {"last": 0.0, "max": 0.0})
            v = float(ev.get("value", 0.0))
            g["last"] = v
            g["max"] = max(g["max"], v)
        elif kind == "histogram":
            hists.setdefault(name, []).append(float(ev.get("value", 0.0)))
    for s in spans.values():
        s["mean_s"] = s["total_s"] / max(s["count"], 1)
    hist_out = {}
    for name, vals in hists.items():
        vals.sort()
        hist_out[name] = {
            "count": len(vals),
            "total": sum(vals),
            "mean": sum(vals) / max(len(vals), 1),
            "p50": _pctl(vals, 0.5),
            "max": vals[-1] if vals else 0.0,
        }

    headline: dict[str, Any] = {}
    step_h = hist_out.get("train.step_s")
    if step_h:
        headline["step_time_p50_s"] = step_h["p50"]
        headline["steps_timed"] = step_h["count"]
    tokens = counters.get("train.tokens")
    if tokens and step_h and step_h["total"] > 0:
        headline["tokens_per_s"] = tokens / step_h["total"]
    if ckpt_saves:
        b = sum(float(e.get("bytes", 0.0)) for e in ckpt_saves)
        d = sum(float(e.get("dur_s", 0.0)) for e in ckpt_saves)
        headline["ckpt_save_bytes"] = b
        if d > 0 and b > 0:
            headline["ckpt_save_gbps"] = b / d / 1e9
    if ckpt_restores:
        b = sum(float(e.get("bytes", 0.0)) for e in ckpt_restores)
        d = sum(float(e.get("dur_s", 0.0)) for e in ckpt_restores)
        if d > 0 and b > 0:
            headline["ckpt_restore_gbps"] = b / d / 1e9
    wait = hist_out.get("data.batch_wait_s")
    if wait:
        headline["data_wait_total_s"] = wait["total"]
    hits = counters.get("data.prefetch_hit", 0.0)
    misses = counters.get("data.prefetch_miss", 0.0)
    if hits + misses > 0:
        headline["prefetch_hit_rate"] = hits / (hits + misses)
    fwds = counters.get("infer.spec.forwards", 0.0)
    committed = counters.get("infer.spec.committed", 0.0)
    if fwds > 0:
        headline["spec_tokens_per_forward"] = committed / fwds
    # Serving observatory (ISSUE 13): request/SLO counts in the headline
    # so a glance at run.json answers "did this replica meet its SLOs".
    if counters.get("serve.requests"):
        headline["serve_requests"] = int(counters["serve.requests"])
    if counters.get("serve.slo_violations"):
        headline["serve_slo_violations"] = int(
            counters["serve.slo_violations"]
        )
    # Fleet observatory (ISSUE 14): how many replicas the fleet poller
    # tracked, so run.json says "this was a fleet run" at a glance.
    if "fleet.size" in gauges:
        headline["fleet_replicas"] = int(gauges["fleet.size"]["last"])
    # Device observatory (ISSUE 15): peak HBM residency fraction, so a
    # glance at run.json answers "how close to OOM did this run live".
    hbm_peak = gauges.get("device.hbm_peak") or gauges.get(
        "device.hbm_used"
    )
    hbm_limit = gauges.get("device.hbm_limit")
    if hbm_peak and hbm_limit and hbm_limit.get("last"):
        headline["hbm_peak_frac"] = round(
            hbm_peak["max"] / hbm_limit["last"], 4
        )

    # Training-health view (ISSUE 3): anomaly/rollback/profile events +
    # last numerics gauges, with headline counts so a glance at run.json
    # answers "did this run diverge".
    from tpuflow.obs.health import health_summary

    health = health_summary(events)
    if health["anomalies"]:
        headline["health_anomalies"] = len(health["anomalies"])
    if health["rollbacks"]:
        headline["health_rollbacks"] = len(health["rollbacks"])
    if health["dropped_events"]:
        headline["obs_dropped_events"] = health["dropped_events"]

    # Goodput ledger (ISSUE 6): the finalize-time accounting — wall time
    # decomposed into productive steps vs compile/restore/data-wait/ckpt/
    # replay/requeue-gap, stitched across gang members and attempts.
    from tpuflow.obs.goodput import compute_goodput

    goodput = compute_goodput(events)
    if goodput["steps_timed"]:
        headline["goodput_fraction"] = round(goodput["fraction"], 4)
    if goodput["buckets"].get("requeue_gap"):
        headline["requeue_gap_s"] = round(
            goodput["buckets"]["requeue_gap"], 3
        )
    if goodput["buckets"].get("resize"):
        # Elastic mesh re-forms (ISSUE 7): what the shrink/grow fences
        # cost, beside the requeue gap they replaced.
        headline["resize_s"] = round(goodput["buckets"]["resize"], 3)
    return {
        "spans": spans,
        "counters": counters,
        "gauges": gauges,
        "histograms": hist_out,
        "health": health,
        "goodput": goodput,
        "headline": headline,
    }
