"""End-to-end request tracing (ISSUE 18): cross-process trace context,
per-hop spans, and the critical-path TTFT attribution.

A request that enters through the front door now crosses three
processes (FrontDoor -> Router forward -> ReplicaGateway ->
ServeEngine) and, on a reroute, multiple replicas. This module is the
host-side, jax-free substrate that stitches those hops back into one
timeline:

- ``TraceContext`` — a W3C-traceparent-style context (32-hex trace id,
  16-hex span id, sampled flag) minted at FrontDoor ingress
  (``maybe_mint``) and reconstructed replica-side from the
  ``traceparent`` HTTP header (``from_traceparent``). The context
  carries the CLIENT request id, so every hop's spans key on the same
  id ``python -m tpuflow.obs trace <request_id>`` looks up.
- **Sampling** is knob-governed: ``TPUFLOW_TRACE`` arms the whole
  layer (disarmed, every integration point is one ``is not None``
  check), ``TPUFLOW_TRACE_SAMPLE`` head-samples at ingress, and
  ``escalate()`` force-records a context after the fact — SLO breach,
  reroute, forward error, queue timeout — so the tail is never lost to
  the head sampler. Unrecorded contexts still PROPAGATE (the flag
  rides the header) so a replica-side escalation can resurrect the
  replica's half of the trace.
- **Spans** buffer on the context (``add_span``) and land in
  per-writer ``trace-<id>.jsonl`` files via the registry's
  single-O_APPEND torn-tail-safe idiom (``write_spans``): concurrent
  writers interleave spans, not bytes, and a crash tears at most the
  final line, which ``read_spans`` skips.
- ``assemble``/``critical_path`` merge the cross-process spans into
  one timeline and attribute TTFT across router queue vs forward
  attempts (each causally linked to the prior attempt; reroutes named)
  vs replica queue vs prefill vs first decode tick — rerouted requests
  attribute across both replicas.

The mergeable TTFT/ITL histograms' Prometheus-style exemplars
(``fleet.MergeableHistogram.observe(v, exemplar=trace_id)``) point back
at these trace ids, so a fleet p99 resolves to a concrete timeline.
"""

from __future__ import annotations

import importlib
import json
import os
import random
import re
import socket
import time
from typing import Any, Iterable

from tpuflow.utils import knobs

# The obs package re-exports the recorder() accessor under the same
# name as its submodule; resolve the MODULE so _rec.event/_rec.counter
# exist regardless of package-init order (the router.py idiom).
_rec = importlib.import_module("tpuflow.obs.recorder")

# W3C traceparent: version 00, 32-hex trace id, 16-hex parent span id,
# 2-hex flags (bit 0 = sampled). Anything else fails closed to None.
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)
_SAFE_RE = re.compile(r"[^A-Za-z0-9._-]+")

# Span names the assembled timeline's critical path attributes TTFT
# over, in hop order. These are span-record names in the trace JSONL,
# not telemetry catalog names (the catalog's trace.* entries count
# spans/escalations, they do not mirror every span).
ROUTER_QUEUE = "router.queue"
ROUTER_FORWARD = "router.forward"
ROUTER_INGRESS = "router.ingress"
ROUTER_REJECT = "router.reject"
GATEWAY_HOLD = "gateway.hold"
GATEWAY_ATTACH = "gateway.attach"
SERVE_QUEUE = "serve.queue"
SERVE_PREFILL = "serve.prefill"
SERVE_FIRST_TICK = "serve.first_tick"
SERVE_DECODE = "serve.decode"
SERVE_LIFECYCLE = "serve.lifecycle"


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def armed() -> bool:
    """Is the tracing layer on at all (``TPUFLOW_TRACE``)? When off,
    ``maybe_mint``/``from_traceparent`` return None and every
    integration point degrades to one ``is not None`` check."""
    return knobs.get_bool("TPUFLOW_TRACE")


class TraceContext:
    """One request's trace identity plus its in-process span buffer.

    ``span_id`` is the CURRENT propagation span (the router mutates it
    per forward attempt so the replica's spans parent to the attempt
    that carried them); ``root_id`` stays the hop's entry span. The
    buffer is owned by exactly one request path per process — no lock.
    """

    __slots__ = (
        "trace_id", "span_id", "root_id", "request_id",
        "sampled", "escalated", "escalate_reason", "spans",
    )

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        request_id: str,
        sampled: bool,
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.root_id = span_id
        self.request_id = request_id
        self.sampled = bool(sampled)
        self.escalated = False
        self.escalate_reason: str | None = None
        self.spans: list[dict] = []

    @property
    def recorded(self) -> bool:
        """Will this context's spans be written at flush?"""
        return self.sampled or self.escalated

    def to_traceparent(self) -> str:
        flag = "01" if self.recorded else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flag}"

    def new_span_id(self) -> str:
        return _hex_id(8)

    def escalate(self, reason: str) -> None:
        """Tail-sampling override: force-record this trace (SLO breach,
        reroute, error, queue timeout). First escalation per context
        emits the evidence event; repeats are silent."""
        if self.escalated:
            return
        self.escalated = True
        self.escalate_reason = str(reason)
        _rec.event(
            "trace.escalate",
            trace=self.trace_id,
            request=self.request_id,
            reason=str(reason),
        )

    def add_span(
        self,
        name: str,
        *,
        ts: float,
        dur_s: float | None = None,
        span_id: str | None = None,
        parent: str | None = None,
        **attrs,
    ) -> str:
        """Buffer one span; returns its span id (the causal handle the
        next hop or the next retry attempt links to)."""
        sid = span_id or self.new_span_id()
        span: dict[str, Any] = {
            "trace": self.trace_id,
            "span": sid,
            "parent": parent,
            "request": self.request_id,
            "name": str(name),
            "ts": round(float(ts), 6),
        }
        if dur_s is not None:
            span["dur_s"] = round(max(float(dur_s), 0.0), 6)
        span.update(attrs)
        self.spans.append(span)
        return sid


# ------------------------------------------------------------- minting
def maybe_mint(request_id: Any) -> TraceContext | None:
    """Ingress mint: None when tracing is disarmed, else a fresh
    context head-sampled per ``TPUFLOW_TRACE_SAMPLE`` (an unsampled
    context still propagates so downstream escalation can record its
    own hops)."""
    if not armed():
        return None
    rate = knobs.get_float("TPUFLOW_TRACE_SAMPLE")
    sampled = rate >= 1.0 or random.random() < rate
    return TraceContext(
        trace_id=_hex_id(16),
        span_id=_hex_id(8),
        request_id=str(request_id or ""),
        sampled=sampled,
    )


def from_traceparent(
    header: str | None, request_id: Any
) -> TraceContext | None:
    """Replica-side context from a propagated ``traceparent`` header.
    None when tracing is disarmed, the header is absent, or it is
    malformed (fail closed — a garbled header must not break serving).
    The hop gets its OWN root span id; the header's span id becomes the
    parent every local span hangs off."""
    if not header or not armed():
        return None
    m = _TRACEPARENT_RE.match(str(header).strip().lower())
    if m is None:
        return None
    ctx = TraceContext(
        trace_id=m.group(1),
        span_id=m.group(2),
        request_id=str(request_id or ""),
        sampled=bool(int(m.group(3), 16) & 1),
    )
    return ctx


# ------------------------------------------------------------- writing
def default_writer_id() -> str:
    """This process's span-file identity: the fleet replica id when the
    deploy manifest set one, else host-pid."""
    rid = knobs.raw("TPUFLOW_FLEET_REPLICA_ID")
    if rid:
        return str(rid)
    return f"{socket.gethostname()}-{os.getpid()}"


def trace_dir() -> str | None:
    """Where this process's trace JSONL lands: ``TPUFLOW_TRACE_DIR``,
    else ``<recorder dir>/trace`` when telemetry is on, else None
    (spans are counted dropped, never raised on)."""
    d = knobs.raw("TPUFLOW_TRACE_DIR")
    if d:
        return d
    rec = _rec.recorder()
    if rec is not None:
        return os.path.join(rec.directory, "trace")
    return None


def write_spans(
    spans: list[dict],
    *,
    writer: str,
    directory: str | None = None,
) -> bool:
    """Append spans to the writer's ``trace-<id>.jsonl`` in ONE
    O_APPEND write (the registry's crash-safe idiom): concurrent
    writers interleave whole spans, never bytes, and a crash tears at
    most the final line, which ``read_spans`` skips. Failures count
    ``trace.dropped`` and return False — tracing never raises into the
    serving path."""
    if not spans:
        return True
    d = directory or trace_dir()
    if d is None:
        _rec.counter("trace.dropped", len(spans))
        return False
    safe = _SAFE_RE.sub("_", str(writer)) or "proc"
    try:
        data = b"".join(
            (
                json.dumps({**s, "writer": str(writer)}, default=str)
                + "\n"
            ).encode()
            for s in spans
        )
        os.makedirs(d, exist_ok=True)
        fd = os.open(
            os.path.join(d, f"trace-{safe}.jsonl"),
            os.O_WRONLY | os.O_CREAT | os.O_APPEND,
            0o644,
        )
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
    except OSError:
        _rec.counter("trace.dropped", len(spans))
        return False
    _rec.counter("trace.spans", len(spans))
    return True


def flush(ctx: TraceContext, *, writer: str | None = None) -> bool:
    """Drain the context's span buffer to disk IF it is recorded
    (head-sampled or escalated); an unrecorded buffer is discarded.
    Idempotent per buffer — the buffer empties either way."""
    spans, ctx.spans = ctx.spans, []
    if not spans:
        return True
    if not ctx.recorded:
        return True
    w = writer or default_writer_id()
    ok = write_spans(spans, writer=w)
    if ok:
        _rec.event(
            "trace.flush",
            trace=ctx.trace_id,
            request=ctx.request_id,
            spans=len(spans),
            writer=w,
            escalated=ctx.escalate_reason,
        )
    return ok


def flush_lifecycle(
    ctx: TraceContext,
    phases: Iterable[dict],
    *,
    engine_request: Any = None,
    writer: str | None = None,
) -> bool:
    """Convert the PR 13 lifecycle phase dicts (submitted -> queued ->
    admitted -> first_token -> ticks -> complete/drained, monotonic
    timestamps) into spans keyed by the PROPAGATED trace/request id and
    flush them — the replica half of the cross-process timeline. Called
    at the request's terminal transition; one ``is not None`` check
    upstream keeps the untraced path free."""
    phases = list(phases)
    if not phases:
        return False
    off = time.time() - time.monotonic()
    first_of: dict[str, dict] = {}
    for p in phases:
        ph = p.get("phase")
        if isinstance(ph, str) and ph not in first_of:
            first_of[ph] = p
    term = None
    for p in reversed(phases):
        if p.get("phase") in ("complete", "drained"):
            term = p
            break
    sub = first_of.get("submitted")
    adm = first_of.get("admitted")
    first = first_of.get("first_token")
    ticks = [p for p in phases if p.get("phase") == "tick"]
    parent = ctx.span_id

    def _seg(name: str, a: dict | None, b: dict | None, **attrs):
        if a is None or b is None:
            return
        ctx.add_span(
            name,
            ts=float(a["t"]) + off,
            dur_s=float(b["t"]) - float(a["t"]),
            parent=parent,
            **attrs,
        )

    queued = first_of.get("queued")
    _seg(
        SERVE_QUEUE, sub, adm,
        reason=queued.get("reason") if queued else None,
    )
    _seg(SERVE_PREFILL, adm, first, bucket=adm.get("bucket") if adm else None)
    _seg(SERVE_FIRST_TICK, first, ticks[0] if ticks else None)
    _seg(
        SERVE_DECODE, first, term,
        ticks=len(ticks),
        tokens=sum(int(t.get("tokens") or 0) for t in ticks),
    )
    if sub is not None and term is not None:
        ctx.add_span(
            SERVE_LIFECYCLE,
            ts=float(sub["t"]) + off,
            dur_s=float(term["t"]) - float(sub["t"]),
            parent=parent,
            terminal=term.get("phase"),
            engine_request=engine_request,
        )
    return flush(ctx, writer=writer)


# ------------------------------------------------------------- reading
def read_spans(directory: str) -> list[dict]:
    """Every well-formed span across the dir's ``*.jsonl`` files. Torn
    final lines (an append died mid-write), corrupt lines, and non-span
    values are skipped — reading a damaged trail never raises."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".jsonl"):
            continue
        try:
            f = open(
                os.path.join(directory, fn),
                encoding="utf-8", errors="replace",
            )
        except OSError:
            continue
        with f:
            for line in f:
                if not line.endswith("\n"):
                    continue  # torn tail: the append died mid-write
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (
                    isinstance(rec, dict)
                    and rec.get("trace")
                    and rec.get("name")
                ):
                    out.append(rec)
    return out


def spans_for_request(directory: str, request_id: Any) -> list[dict]:
    rid = str(request_id)
    return [
        s for s in read_spans(directory)
        if str(s.get("request")) == rid
    ]


def spans_for_trace(directory: str, trace_id: str) -> list[dict]:
    tid = str(trace_id)
    return [
        s for s in read_spans(directory) if str(s.get("trace")) == tid
    ]


# ------------------------------------------------------------ assembly
def _dur(s: dict) -> float:
    v = s.get("dur_s")
    return float(v) if isinstance(v, (int, float)) else 0.0


def critical_path(spans: list[dict]) -> list[dict]:
    """The TTFT critical path through the merged timeline, one segment
    per hop: router queue -> each failed forward attempt (+ its
    backoff) -> the reroute edge (named, with from/to replicas) ->
    replica queue -> prefill -> first decode tick. Rerouted requests
    attribute across both replicas — the failed attempt's wall lives on
    the dead replica, the serve segments on the winner."""
    path: list[dict] = []
    queue_s = sum(
        _dur(s) for s in spans if s.get("name") == ROUTER_QUEUE
    )
    if any(s.get("name") == ROUTER_QUEUE for s in spans):
        path.append(
            {"segment": "router_queue", "dur_s": round(queue_s, 6)}
        )
    forwards = sorted(
        (s for s in spans if s.get("name") == ROUTER_FORWARD),
        key=lambda s: int(s.get("attempt") or 0),
    )
    last_failed = None
    for f in forwards:
        if f.get("ok"):
            if f.get("reroute"):
                path.append(
                    {
                        "segment": "reroute",
                        "from": last_failed,
                        "to": f.get("replica"),
                        "attempt": f.get("attempt"),
                    }
                )
            continue
        seg = {
            "segment": "forward_failed",
            "replica": f.get("replica"),
            "attempt": f.get("attempt"),
            "dur_s": round(_dur(f), 6),
            "error": f.get("error"),
        }
        if isinstance(f.get("backoff_s"), (int, float)):
            seg["backoff_s"] = round(float(f["backoff_s"]), 6)
        path.append(seg)
        last_failed = f.get("replica")
    for name, label in (
        (SERVE_QUEUE, "replica_queue"),
        (SERVE_PREFILL, "prefill"),
        (SERVE_FIRST_TICK, "first_decode_tick"),
        (SERVE_DECODE, "decode"),
    ):
        for s in spans:
            if s.get("name") == name:
                path.append(
                    {
                        "segment": label,
                        "replica": s.get("writer"),
                        "dur_s": round(_dur(s), 6),
                    }
                )
                break
    return path


def assemble(spans: list[dict]) -> dict[str, Any] | None:
    """Merge one request's cross-process spans into a single timeline:
    spans in wall-clock order, the participating writers, the client-
    observed wall (the ingress span when present, else the span
    envelope), the critical path, and the TTFT breakdown. None when
    there is nothing to assemble."""
    spans = [s for s in spans if isinstance(s.get("ts"), (int, float))]
    if not spans:
        return None
    spans = sorted(spans, key=lambda s: (float(s["ts"]), str(s.get("name"))))
    t0 = float(spans[0]["ts"])
    ingress = next(
        (s for s in spans if s.get("name") == ROUTER_INGRESS), None
    )
    envelope = max(float(s["ts"]) + _dur(s) for s in spans) - t0
    wall = _dur(ingress) if ingress is not None and _dur(ingress) else envelope
    path = critical_path(spans)
    ttft_parts = {
        "router_queue_s": 0.0,
        "forward_failed_s": 0.0,
        "backoff_s": 0.0,
        "replica_queue_s": 0.0,
        "prefill_s": 0.0,
        "first_tick_s": 0.0,
    }
    for seg in path:
        if seg["segment"] == "router_queue":
            ttft_parts["router_queue_s"] += seg["dur_s"]
        elif seg["segment"] == "forward_failed":
            ttft_parts["forward_failed_s"] += seg["dur_s"]
            ttft_parts["backoff_s"] += seg.get("backoff_s", 0.0)
        elif seg["segment"] == "replica_queue":
            ttft_parts["replica_queue_s"] += seg["dur_s"]
        elif seg["segment"] == "prefill":
            ttft_parts["prefill_s"] += seg["dur_s"]
        elif seg["segment"] == "first_decode_tick":
            ttft_parts["first_tick_s"] += seg["dur_s"]
    ttft_parts = {k: round(v, 6) for k, v in ttft_parts.items()}
    return {
        "request": spans[0].get("request"),
        "trace": spans[0].get("trace"),
        "spans": spans,
        "writers": sorted(
            {str(s.get("writer")) for s in spans if s.get("writer")}
        ),
        "wall_s": round(wall, 6),
        "rerouted": any(seg["segment"] == "reroute" for seg in path),
        "critical_path": path,
        "ttft_breakdown": ttft_parts,
        "ttft_s": round(
            sum(v for k, v in ttft_parts.items() if k != "backoff_s")
            + ttft_parts["backoff_s"],
            6,
        ),
    }


def format_timeline(assembled: dict[str, Any]) -> list[str]:
    """Human lines for ``python -m tpuflow.obs trace``: the merged
    timeline (one line per span, offsets from the first span), then the
    critical-path TTFT breakdown."""
    out = [
        f"trace {assembled.get('trace')} request "
        f"{assembled.get('request')!r}: {len(assembled['spans'])} spans "
        f"across {', '.join(assembled['writers']) or '?'} — wall "
        f"{assembled['wall_s']:.4f}s"
        + (" [REROUTED]" if assembled.get("rerouted") else "")
    ]
    t0 = float(assembled["spans"][0]["ts"])
    for s in assembled["spans"]:
        extra = []
        for k in ("replica", "attempt", "ok", "reroute", "status",
                  "terminal", "error", "attached"):
            if s.get(k) is not None:
                extra.append(f"{k}={s[k]}")
        dur = f" dur={_dur(s):.4f}s" if s.get("dur_s") is not None else ""
        out.append(
            f"  +{float(s['ts']) - t0:8.4f}s  "
            f"{str(s.get('writer') or '?'):<16} {s['name']:<18}"
            f"{dur}"
            + (f"  [{' '.join(extra)}]" if extra else "")
        )
    out.append("critical path (TTFT attribution):")
    for seg in assembled["critical_path"]:
        if seg["segment"] == "reroute":
            out.append(
                f"  reroute: {seg.get('from')} -> {seg.get('to')} "
                f"(attempt {seg.get('attempt')})"
            )
            continue
        who = f" @{seg['replica']}" if seg.get("replica") else ""
        out.append(
            f"  {seg['segment']:<18} {seg.get('dur_s', 0.0):.4f}s{who}"
        )
    out.append(
        "ttft ~ " + " + ".join(
            f"{k.removesuffix('_s')}={v:.4f}s"
            for k, v in assembled["ttft_breakdown"].items()
            if v
        )
    )
    return out
