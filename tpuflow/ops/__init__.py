"""TPU compute kernels: XLA-fused ops and Pallas kernels for the hot paths."""

from tpuflow.ops.attention import attention, xla_attention

__all__ = ["attention", "xla_attention"]
