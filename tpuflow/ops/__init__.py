"""TPU compute kernels: XLA-fused ops and Pallas kernels for the hot paths."""

from tpuflow.ops.attention import (
    attention,
    resolve_attention_impl,
    xla_attention,
)
from tpuflow.ops.int8_matmul import (
    int8_matmul,
    quantize_rows,
    resolve_int8_impl,
)

__all__ = [
    "attention",
    "int8_matmul",
    "quantize_rows",
    "resolve_attention_impl",
    "resolve_int8_impl",
    "xla_attention",
]
