"""TPU compute kernels: XLA-fused ops and Pallas kernels for the hot paths."""

from tpuflow.ops.attention import (
    attention,
    resolve_attention_impl,
    xla_attention,
)

__all__ = ["attention", "resolve_attention_impl", "xla_attention"]
