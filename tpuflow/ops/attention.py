"""Attention implementations with one call signature, selectable per model.

``attention(q, k, v, *, causal, impl)`` with q/k/v shaped (B, T, H, D).
Implementations:

- ``"xla"``   — plain einsum softmax attention; XLA fuses it well for short
  sequences and it runs on any backend (the CPU test mesh included).
- ``"flash"`` — Pallas TPU blockwise (flash) attention kernel, O(T) memory
  (tpuflow.ops.flash_attention).
- ``"ring"``  — ring attention over the 'seq' mesh axis for long-context
  sequence parallelism (tpuflow.parallel.ring_attention): KV blocks rotate
  around the ring via collective-permute while each shard computes blockwise
  attention — the TPU-native long-context strategy (absent from the reference,
  which has no attention at all; SURVEY.md §5 long-context).
- ``"ulysses"`` — all-to-all sequence parallelism over 'seq'
  (tpuflow.parallel.ulysses): all_to_alls swap q/k/v to full-sequence /
  head-sharded layout, attention runs locally with plain causal masking,
  one more all_to_all swaps the output back — 4 collectives per call vs
  ring's s-step rotation.

The reference has no attention op anywhere (its model is an image MLP,
my_ray_module.py:94-112); these exist for the GPT-2 acceptance config and the
framework's first-class long-context support.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def xla_attention(q, k, v, *, causal: bool = True):
    """Reference einsum attention. q,k,v: (B, T, H, D) → (B, T, H, D).

    Softmax statistics in float32 regardless of input dtype (bf16-safe on the
    MXU: the matmuls stay bf16, the normalization doesn't lose precision).
    """
    B, T, H, D = q.shape
    scale = 1.0 / jnp.sqrt(D).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, k.shape[1]), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


_DEFAULT_FLASH_MIN_SEQ = 2048
_flash_tuning_cache: dict | None = None
_warned_malformed_env = False


def flash_tuning_path() -> str:
    """Where ``bench.py`` persists the measured flash/XLA fwd+bwd
    crossover on this host: ``$TPUFLOW_HOME/flash_tuning.json`` with
    ``{"flash_min_seq": T}``."""
    import os

    home = os.environ.get(
        "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
    )
    return os.path.join(home, "flash_tuning.json")


def _flash_min_seq() -> int:
    """Dispatch threshold resolution: TPUFLOW_FLASH_MIN_SEQ env var beats
    the host's measured tuning file beats the shipped default. A MALFORMED
    env var falls through to the tuning-file lookup (the host's measured
    crossover — strictly better information than the shipped constant)
    and warns once per process, through the obs stream when one is live.
    The file read is cached per process (this runs at trace time)."""
    import json
    import os

    global _flash_tuning_cache, _warned_malformed_env
    env = os.environ.get("TPUFLOW_FLASH_MIN_SEQ")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            if not _warned_malformed_env:
                _warned_malformed_env = True
                import warnings

                from tpuflow import obs

                warnings.warn(
                    f"TPUFLOW_FLASH_MIN_SEQ={env!r} is not an integer; "
                    "falling through to the tuning file / default",
                    stacklevel=2,
                )
                obs.event("warn.flash_min_seq_malformed", value=env)
            # fall through to the measured tuning file below
    if _flash_tuning_cache is None:
        try:
            with open(flash_tuning_path()) as f:
                _flash_tuning_cache = json.load(f)
        except (OSError, ValueError):
            _flash_tuning_cache = {}
    v = _flash_tuning_cache.get("flash_min_seq")
    return v if isinstance(v, int) and v > 0 else _DEFAULT_FLASH_MIN_SEQ


def attention(q, k, v, *, causal: bool = True, impl: str = "xla"):
    """Dispatch to the selected implementation (see module docstring).

    ``impl='auto'`` picks by measured crossover: flash only on TPU at
    T >= the resolved threshold (TPUFLOW_FLASH_MIN_SEQ env var, else the
    host's bench-measured tuning file — ``flash_tuning_path()`` — else
    2048, the r4 measured-win point: on-chip evidence had fwd+bwd
    winning at T=2048 by 1.73x while the T=512 record proved timing-
    artifact-suspect), and XLA everywhere else — CPU always takes XLA
    (flash there is interpret-mode, for tests only).
    """
    if impl == "auto":
        # NB: resolved at trace time — under jit the choice is baked into
        # the compiled program for each shape; changing the env var after
        # compilation does not retune existing executables.
        on_tpu = jax.default_backend() == "tpu"
        impl = "flash" if (on_tpu and q.shape[1] >= _flash_min_seq()) \
            else "xla"
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "flash":
        from tpuflow.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        from tpuflow.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, causal=causal)
    if impl == "ulysses":
        from tpuflow.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal)
    raise KeyError(
        f"unknown attention impl {impl!r}; use xla|flash|ring|ulysses"
    )
