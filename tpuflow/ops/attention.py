"""Attention implementations with one call signature, selectable per model.

``attention(q, k, v, *, causal, impl)`` with q/k/v shaped (B, T, H, D).
Implementations:

- ``"xla"``   — plain einsum softmax attention; XLA fuses it well for short
  sequences and it runs on any backend (the CPU test mesh included).
- ``"flash"`` — Pallas TPU blockwise (flash) attention kernel, O(T) memory
  (tpuflow.ops.flash_attention).
- ``"ring"``  — ring attention over the 'seq' mesh axis for long-context
  sequence parallelism (tpuflow.parallel.ring_attention): KV blocks rotate
  around the ring via collective-permute while each shard computes blockwise
  attention — the TPU-native long-context strategy (absent from the reference,
  which has no attention at all; SURVEY.md §5 long-context).
- ``"ulysses"`` — all-to-all sequence parallelism over 'seq'
  (tpuflow.parallel.ulysses): all_to_alls swap q/k/v to full-sequence /
  head-sharded layout, attention runs locally with plain causal masking,
  one more all_to_all swaps the output back — 4 collectives per call vs
  ring's s-step rotation.

The reference has no attention op anywhere (its model is an image MLP,
my_ray_module.py:94-112); these exist for the GPT-2 acceptance config and the
framework's first-class long-context support.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from tpuflow.utils import knobs


def xla_attention(q, k, v, *, causal: bool = True):
    """Reference einsum attention. q,k,v: (B, T, H, D) → (B, T, H, D).

    Softmax statistics in float32 regardless of input dtype (bf16-safe on the
    MXU: the matmuls stay bf16, the normalization doesn't lose precision).
    """
    B, T, H, D = q.shape
    scale = 1.0 / jnp.sqrt(D).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, k.shape[1]), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# Shipped defaults for the auto-dispatch thresholds, from the on-chip
# evidence (BENCH_r05): fwd+bwd needs T >= 2048 (at T=512 the flash
# backward LOST to XLA at 0.2x while fwd won 2.73x — the two paths have
# genuinely different crossovers, so they carry independent thresholds);
# fwd-only (decode prefill, scoring without grad) wins from T >= 512.
_DEFAULT_FLASH_MIN_SEQ = 2048
_DEFAULT_FLASH_MIN_SEQ_FWD = 512
_flash_tuning_cache: dict | None = None
_warned_malformed_env = False
_warned_malformed_tuning = False


def flash_tuning_path() -> str:
    """Where ``bench.py`` persists the measured flash/XLA crossovers on
    this host: ``$TPUFLOW_HOME/flash_tuning.json`` with
    ``{"flash_min_seq": T_fwdbwd, "flash_min_seq_fwd": T_fwd,
    "flash_min_seq_bwd": T_bwdonly}``."""
    import os

    home = knobs.raw(
        "TPUFLOW_HOME", os.path.join(os.path.expanduser("~"), ".tpuflow")
    )
    return os.path.join(home, "flash_tuning.json")


def _flash_tuning() -> dict:
    import json

    global _flash_tuning_cache
    if _flash_tuning_cache is None:
        try:
            with open(flash_tuning_path()) as f:
                _flash_tuning_cache = json.load(f)
        except (OSError, ValueError):
            _flash_tuning_cache = {}
    return _flash_tuning_cache


def _flash_min_seq(*, needs_bwd: bool = True) -> int:
    """Dispatch threshold resolution, independently for the fwd+bwd
    (training) and fwd-only (inference) paths: the env var
    (TPUFLOW_FLASH_MIN_SEQ / TPUFLOW_FLASH_MIN_SEQ_FWD) beats the host's
    measured tuning file beats the shipped default. A MALFORMED env var
    falls through to the tuning-file lookup (the host's measured
    crossover — strictly better information than the shipped constant)
    and warns once per process, through the obs stream when one is live.
    An unset fwd-only env var falls back to the fwd+bwd env var scaled
    by nothing — i.e. only its own sources; the two paths never borrow
    each other's thresholds (BENCH_r05: at T=512 fwd wins 2.73x while
    fwd+bwd loses at 0.2x). The file read is cached per process (this
    runs at trace time).

    The training path additionally consults the fitted BWD-ONLY
    crossover (ISSUE 10 satellite; bench's T512/T2048 ``jax.vjp`` timing
    split, persisted as ``flash_min_seq_bwd``): the effective fwd+bwd
    threshold is the max of the valid measured entries — below the
    measured backward-kernel crossover the bwd kernels are a MEASURED
    loss, so fwd+bwd dispatch must pick XLA there even when the fwd+bwd
    composition point is absent or was discarded as timing-suspect. A
    malformed tuning entry (present but not a positive integer) is
    ignored with a once-per-process warning; no valid entry at all falls
    back to the shipped default."""
    import os

    global _warned_malformed_env
    env_name = (
        "TPUFLOW_FLASH_MIN_SEQ" if needs_bwd else "TPUFLOW_FLASH_MIN_SEQ_FWD"
    )
    # tpulint: disable=knob-dynamic -- env_name is one of two literal
    # TPUFLOW_FLASH_MIN_SEQ* names selected two lines up; both are
    # declared and the string-literal rule validates them.
    env = knobs.raw(env_name)
    if env is not None:
        try:
            return int(env)
        except ValueError:
            if not _warned_malformed_env:
                _warned_malformed_env = True
                import warnings

                from tpuflow import obs

                warnings.warn(
                    f"{env_name}={env!r} is not an integer; "
                    "falling through to the tuning file / default",
                    stacklevel=2,
                )
                obs.event("warn.flash_min_seq_malformed", value=env)
            # fall through to the measured tuning file below
    keys = (
        ("flash_min_seq", "flash_min_seq_bwd")
        if needs_bwd
        else ("flash_min_seq_fwd",)
    )
    fitted = [_tuning_entry(k) for k in keys]
    fitted = [v for v in fitted if v is not None]
    if fitted:
        return max(fitted)
    return (
        _DEFAULT_FLASH_MIN_SEQ if needs_bwd else _DEFAULT_FLASH_MIN_SEQ_FWD
    )


def _tuning_entry(key: str) -> int | None:
    """One tuning-file entry, validated: a positive int passes through,
    an absent key is None, and a MALFORMED value (bench never writes one,
    but a hand-edited file might) is ignored with the same
    once-per-process warning discipline as the env path — a typo'd
    tuning file must degrade to the shipped defaults, never crash a
    trace or silently dispatch off a garbage threshold."""
    global _warned_malformed_tuning
    v = _flash_tuning().get(key)
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, int) or v <= 0:
        if not _warned_malformed_tuning:
            _warned_malformed_tuning = True
            import warnings

            from tpuflow import obs

            warnings.warn(
                f"flash tuning entry {key}={v!r} is not a positive "
                "integer; ignoring it",
                stacklevel=3,
            )
            obs.event("warn.flash_min_seq_malformed", value=repr(v))
        return None
    return v


def resolve_attention_impl(
    impl: str, seq_len: int, *, needs_bwd: bool = True,
    backend: str | None = None,
) -> str:
    """Resolve ``impl='auto'`` to a concrete implementation for one
    (backend, seq_len, path) combination — factored out of ``attention``
    so the dispatch choice is unit-testable without a TPU. Non-'auto'
    impls pass through unchanged. ``needs_bwd`` selects which measured
    crossover applies: the fwd+bwd threshold for calls that will be
    differentiated (training), the fwd-only threshold for pure-inference
    forwards (decode prefill) — see ``_flash_min_seq``."""
    if impl != "auto":
        return impl
    backend = backend if backend is not None else jax.default_backend()
    if backend == "tpu" and seq_len >= _flash_min_seq(needs_bwd=needs_bwd):
        return "flash"
    return "xla"


def attention(q, k, v, *, causal: bool = True, impl: str = "xla",
              needs_bwd: bool = True):
    """Dispatch to the selected implementation (see module docstring).

    ``impl='auto'`` picks by measured crossover: flash only on TPU at
    T >= the resolved threshold — the fwd+bwd threshold when
    ``needs_bwd`` (TPUFLOW_FLASH_MIN_SEQ / tuning-file ``flash_min_seq``
    / 2048: on-chip evidence had fwd+bwd winning at T=2048 by 1.73x and
    LOSING at T=512 by 0.2x), else the fwd-only threshold
    (TPUFLOW_FLASH_MIN_SEQ_FWD / ``flash_min_seq_fwd`` / 512, where fwd
    alone already won 2.73x) — and XLA everywhere else; CPU always takes
    XLA (flash there is interpret-mode, for tests only).
    """
    if impl == "auto":
        # NB: resolved at trace time — under jit the choice is baked into
        # the compiled program for each shape; changing the env var after
        # compilation does not retune existing executables.
        impl = resolve_attention_impl(
            "auto", q.shape[1], needs_bwd=needs_bwd
        )
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "flash":
        from tpuflow.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        from tpuflow.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, causal=causal)
    if impl == "ulysses":
        from tpuflow.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal)
    raise KeyError(
        f"unknown attention impl {impl!r}; use xla|flash|ring|ulysses"
    )
