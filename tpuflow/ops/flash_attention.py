"""Blockwise / flash attention: O(T) memory attention for TPU.

Two tiers with identical numerics:

- ``blockwise_attention`` — pure-JAX online-softmax attention via ``lax.scan``
  over KV chunks. O(block) memory instead of O(T^2), differentiable, runs on
  any backend; the building block of ring attention.
- ``flash_attention`` — Pallas TPU kernels (MXU matmuls in the q/k blocks,
  float32 online-softmax state in VMEM scratch). Forward saves only
  (O, logsumexp); backward recomputes P inside two FUSED Pallas kernels
  (dq + row-delta; dk/dv merged) — the flash-style compute-for-memory
  trade. ``TPUFLOW_FLASH_BWD`` selects the backward: ``fused`` (default;
  the ISSUE 10 two-kernel design), ``split`` (the previous per-visit
  row-delta kernels, kept one release as the on-chip regression
  reference), ``blockwise`` (the pure-JAX recompute VJP).

The reference has no attention anywhere (its model is an image MLP,
my_ray_module.py:94-112); these exist for the GPT-2 acceptance config and
first-class long-context support (SURVEY.md §5).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from tpuflow.utils import knobs

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 spells it TPUCompilerParams; alias so call sites stay on
    # the current name.
    pltpu.CompilerParams = pltpu.TPUCompilerParams

_NEG_INF = -1e30


def _chunk_positions(t: int, block: int):
    n = t // block
    return jnp.arange(n)[:, None] * block + jnp.arange(block)[None, :]


def blockwise_attention(q, k, v, *, causal: bool = True, block_k: int = 512):
    """Online-softmax attention, scanning KV in chunks. q,k,v: (B,T,H,D)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    block_k = min(block_k, Tk)
    if Tk % block_k:
        return _reference_attention(q, k, v, causal=causal)
    nk = Tk // block_k
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    q32 = q.astype(jnp.float32)
    kc = k.reshape(B, nk, block_k, H, D).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(B, nk, block_k, H, D).transpose(1, 0, 2, 3, 4)
    k_pos = _chunk_positions(Tk, block_k)
    q_pos = jnp.arange(Tq)

    def body(carry, inp):
        m, l, acc = carry
        k_blk, v_blk, kp = inp
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            mask = q_pos[:, None] >= kp[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), (kc, vc, k_pos))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _reference_attention(q, k, v, *, causal: bool):
    from tpuflow.ops.attention import xla_attention

    return xla_attention(q, k, v, causal=causal)


# ----------------------------------------------------------- pallas kernel
def _masked_scores(q_ref, k_ref, iq, ik, *, scale, causal, block_q, block_k):
    """Scaled (block_q, block_k) f32 score tile with the causal mask applied.

    Shared by the forward and both backward kernels so the mask/scale
    semantics cannot diverge between them. MXU feeds stay in the input dtype
    (bf16 multiplies at full MXU rate); accumulation is f32 via
    preferred_element_type.
    """
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _fwd_kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                      **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, None, m_scr, l_scr, acc_scr, **kw)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # Only the softmax statistics run in f32 on the VPU.
        s = _masked_scores(
            q_ref, k_ref, iq, ik,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        m_old = m_scr[:, 0]
        m_new = jnp.maximum(m_old, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_old - m_new)
        l_new = l_scr[:, 0] * corr + p.sum(axis=-1)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_new

    if causal:
        # Causal block skip: a KV block strictly above the diagonal is fully
        # masked — skip its compute entirely (~2x fewer FLOPs at long T).
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _maybe():
            _compute()
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _final():
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, 0], 1e-30)[:, None]
        ).astype(o_ref.dtype)
        if lse_ref is not None:
            # Mosaic requires ≥(8,128)-tileable outputs: lse rides a full
            # 128-lane minor dim (value broadcast across lanes), the same
            # layout the reference TPU flash kernels use for their softmax
            # residuals. Only the VJP forward emits it — the primal path
            # skips the output entirely (pallas outputs are opaque to XLA
            # DCE, so an unused lse would still be written to HBM).
            lse = m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
            lse_ref[0] = jax.lax.broadcast_in_dim(
                lse, lse_ref.shape[1:], (0,)
            )


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               interpret: bool, *, with_lse: bool = False):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    grid = (B * H, Tq // block_q, Tk // block_k)
    o_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    o_shape = jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype)
    if with_lse:
        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        )
        out_specs = [
            o_spec,
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ]
        out_shape = [
            o_shape,
            # logsumexp per row — the softmax residual the backward kernels
            # need to recompute P without re-running the online softmax.
            # Broadcast over a 128-lane minor dim for TPU tiling.
            jax.ShapeDtypeStruct((B * H, Tq, 128), jnp.float32),
        ]
    else:
        # Primal/inference path: no lse output at all — pallas outputs are
        # written unconditionally, so emitting-then-dropping it would cost
        # a full (BH, Tq, 128) f32 HBM write per call.
        kernel = functools.partial(
            _fwd_kernel_nolse, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        )
        out_specs = o_spec
        out_shape = o_shape
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom (col 0)
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
        # batch·head and q-block programs are independent; the k loop is a
        # sequential reduction (carries the softmax state in scratch).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf)
    out, lse = res if with_lse else (res, None)
    out = out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    if with_lse:
        return out, lse
    return out


# -------------------------------------------------- pallas backward kernels
# FlashAttention-2-style backward: P is recomputed inside the kernels from
# (q, k, lse) — the compute-for-memory trade — in two kernels so each
# accumulates over its own sequential axis without atomics:
#   dq kernel : grid (BH, nq, nk), k innermost — dq_i += dS_ij K_j
#   dkv kernel: grid (BH, nk, nq), q innermost — dK_j += dS_ij^T Q_i,
#                                                dV_j += P_ij^T dO_i
# with dS = P ∘ (dP − D), dP = dO V^T, D = rowsum(dO ∘ O).
#
# Two shapes of the pair exist (ISSUE 10):
#
# - FUSED (default): the dq kernel computes D once per q block at its
#   FIRST kv-block visit (f32 scratch, not per visit) and packs the two
#   per-row softmax residuals into ONE lane-addressed (BH, Tq, 128) f32
#   tensor — lane 0 = lse (bit-copied from the forward residual), lane 1
#   = D. The merged dk/dv kernel then reads that single residual instead
#   of (lse + o): its q-innermost walk re-streams each q row's operands
#   nk times, so dropping the o stream and the per-visit rowsum removes
#   one full HBM pass and nk-1 VPU reduces per row — the short-T regime
#   where BENCH_r05 measured the backward losing 5x to XLA is exactly
#   where that per-visit residual traffic rivals the useful q/k/v bytes.
# - SPLIT (TPUFLOW_FLASH_BWD=split, one release as the regression
#   reference): the previous kernels — D recomputed from (o, do) inside
#   EVERY block visit of both kernels.
#
# The two are bit-identical by construction (same op order; D is the
# same f32 value whether recomputed or round-tripped through f32 HBM) —
# pinned in interpret mode by tests/test_attention.py, and raced on chip
# by the bench flash leg's fused-vs-split column.


def _row_delta(o_ref, do_ref):
    """D_i = rowsum(dO ∘ O) for the current q block → (block_q, 1) f32."""
    return jnp.sum(
        do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
        axis=-1, keepdims=True,
    )


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        s = _masked_scores(
            q_ref, k_ref, iq, ik,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _row_delta(o_ref, do_ref)) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _maybe():
            _compute()
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                    dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                    block_k):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        s = _masked_scores(
            q_ref, k_ref, iq, ik,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        p = jnp.exp(s - lse_ref[0][:, :1])  # (block_q, block_k)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - _row_delta(o_ref, do_ref)) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # A q block entirely before this k block contributes nothing.
        @pl.when(iq * block_q + block_q - 1 >= ik * block_k)
        def _maybe():
            _compute()
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# Lane indices of the packed per-row residual tensor the fused backward
# kernels share: lane 0 carries lse (bit-copied from the forward
# residual), lane 1 carries D = rowsum(dO ∘ O). The kernels only ever
# read one column of a lane-broadcast residual anyway, so the 128-lane
# minor dim Mosaic requires is free real estate — packing both residuals
# into one tensor halves the dkv kernel's residual streams.
_RES_LSE_LANE = 0
_RES_DELTA_LANE = 1


def _bwd_dq_fused_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                         dq_ref, res_ref, dq_scr, delta_scr, *, scale,
                         causal, block_q, block_k):
    """dq + row-delta in one pass (ISSUE 10 fused design).

    Identical math to ``_bwd_dq_kernel`` except D is computed ONCE per q
    block — at the first kv-block visit, into f32 scratch — instead of
    per visit, and the (lse, D) pair is written out as the lane-packed
    residual the fused dkv kernel consumes. o/do/lse block fetches are
    hoisted by Mosaic (their index maps ignore the kv grid axis), so the
    saving here is the nk-1 redundant VPU reduces; the HBM saving lands
    in the dkv kernel, which stops streaming o entirely.
    """
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        # D_i once per q block. Under the causal block skip ik == 0 is
        # never skipped (the diagonal block's kv start is 0), so the
        # scratch and the residual are always populated.
        delta = _row_delta(o_ref, do_ref)  # (block_q, 1) f32
        delta_scr[:] = jax.lax.broadcast_in_dim(
            delta[:, 0], delta_scr.shape, (0,)
        )
        lane = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, res_ref.shape[-1]), 1
        )
        # Lane 0 keeps the forward's lse bits exactly (bit-parity with
        # the split kernels, which read lse straight from the forward).
        res_ref[0] = jnp.where(
            lane == _RES_DELTA_LANE, delta_scr[:], lse_ref[0]
        )

    def _compute():
        s = _masked_scores(
            q_ref, k_ref, iq, ik,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_scr[:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ik * block_k <= iq * block_q + block_q - 1)
        def _maybe():
            _compute()
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_fused_kernel(q_ref, k_ref, v_ref, do_ref, res_ref, dk_ref,
                          dv_ref, dk_scr, dv_scr, *, scale, causal,
                          block_q, block_k):
    """Merged dk/dv over one KV-grid walk, consuming the packed residual.

    vs ``_bwd_dkv_kernel``: o is not an input and D is not recomputed —
    lse and D both come out of the single lane-packed residual the fused
    dq kernel wrote. The q-innermost walk re-streams every q-indexed
    operand nk times, so this drops one full (BH, Tq, D) HBM stream per
    outer kv block plus the per-visit rowsum.
    """
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        s = _masked_scores(
            q_ref, k_ref, iq, ik,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        lse = res_ref[0][:, _RES_LSE_LANE:_RES_LSE_LANE + 1]
        p = jnp.exp(s - lse)  # (block_q, block_k)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = res_ref[0][:, _RES_DELTA_LANE:_RES_DELTA_LANE + 1]
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # A q block entirely before this k block contributes nothing.
        @pl.when(iq * block_q + block_q - 1 >= ik * block_k)
        def _maybe():
            _compute()
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_fused(q, k, v, o, lse, g, causal, block_q, block_k,
                     interpret):
    """The fused two-kernel backward (default; see the section comment)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    BH = B * H

    def flat(x, T):
        return x.transpose(0, 2, 1, 3).reshape(BH, T, D)

    qf, kf, vf = flat(q, Tq), flat(k, Tk), flat(v, Tk)
    of, gf = flat(o, Tq), flat(g, Tq)
    if lse.ndim == 2:  # TPUFLOW_FLASH_LSE=compact residual — reinflate
        lse = jnp.broadcast_to(lse[..., None], (*lse.shape, 128))

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    lse_spec = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
    dq, res = pl.pallas_call(
        functools.partial(
            _bwd_dq_fused_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            q_spec,
            q_spec,
            lse_spec,
        ],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            # The packed (lse, D) residual for the dkv kernel.
            jax.ShapeDtypeStruct((BH, Tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    k_spec = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    qi_spec = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    resi_spec = pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_fused_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, Tk // block_k, Tq // block_q),
        in_specs=[qi_spec, k_spec, k_spec, qi_spec, resi_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf, gf, res)

    def unflat(x, T):
        return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    return unflat(dq, Tq), unflat(dk, Tk), unflat(dv, Tk)


def _flash_bwd_split(q, k, v, o, lse, g, causal, block_q, block_k,
                     interpret):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    BH = B * H

    def flat(x, T):
        return x.transpose(0, 2, 1, 3).reshape(BH, T, D)

    qf, kf, vf = flat(q, Tq), flat(k, Tk), flat(v, Tk)
    of, gf = flat(o, Tq), flat(g, Tq)
    # lse normally arrives in the kernels' native (BH, Tq, 128)
    # lane-broadcast layout straight from the forward — no
    # slice/rebroadcast round trip (at short T those two extra HBM
    # passes rival the useful q/k/v traffic). Under
    # TPUFLOW_FLASH_LSE=compact the residual is (BH, Tq) and is
    # reinflated here.
    if lse.ndim == 2:
        lse = jnp.broadcast_to(lse[..., None], (*lse.shape, 128))

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    lse_spec = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, Tq // block_q, Tk // block_k),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            q_spec,
            q_spec,
            lse_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    k_spec = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    qi_spec = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    lsei_spec = pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, Tk // block_k, Tq // block_q),
        in_specs=[qi_spec, k_spec, k_spec, qi_spec, qi_spec, lsei_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    def unflat(x, T):
        return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    return unflat(dq, Tq), unflat(dk, Tk), unflat(dv, Tk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    o, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, with_lse=True
    )
    # The residual keeps the kernel's native (BH, Tq, 128) lane-broadcast
    # layout by default (the same choice as the reference TPU flash
    # kernels, which hold their l/m residuals this way): slicing to a
    # compact (BH, Tq) here and re-broadcasting in the backward costs two
    # full-array HBM passes per step, which at short T dominates the
    # backward. The 128x f32 residual is transient per layer under remat;
    # WITHOUT remat it is held for every layer simultaneously and roughly
    # doubles attention's residual bytes — TPUFLOW_FLASH_LSE=compact
    # restores the small residual for memory-bound remat-off configs
    # (trading the two HBM passes back).
    if knobs.raw("TPUFLOW_FLASH_LSE") == "compact":
        return o, (q, k, v, o, lse[..., 0])
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    mode = knobs.raw("TPUFLOW_FLASH_BWD", "fused")
    if mode == "blockwise":
        # Fallback: recompute through the O(T)-memory blockwise path.
        _, vjp = jax.vjp(
            lambda q, k, v: blockwise_attention(q, k, v, causal=causal),
            q, k, v,
        )
        return vjp(g)
    interpret = jax.default_backend() != "tpu"
    if mode == "split":
        # The pre-ISSUE-10 two-pass kernels, kept one release as the
        # on-chip regression reference (the bench flash leg races them
        # against the fused pair and fails on a fused loss at T2048).
        return _flash_bwd_split(
            q, k, v, o, lse, g, causal, block_q, block_k, interpret
        )
    # Trace-time marker: which compiled programs took the fused backward
    # (each jit trace of a differentiated flash call lands here once).
    from tpuflow import obs

    obs.event(
        "ops.flash_bwd_fused", seq=int(q.shape[1]), heads=int(q.shape[2]),
        causal=bool(causal), block_q=block_q, block_k=block_k,
    )
    return _flash_bwd_fused(
        q, k, v, o, lse, g, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, block_q: int = 256, block_k: int = 256
):
    """Pallas TPU flash attention. q,k,v: (B,T,H,D) → (B,T,H,D).

    Falls back to ``blockwise_attention`` when shapes don't tile (T not
    divisible by the blocks, or tiny head_dim on CPU interpret mode).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k or D % 8:
        return blockwise_attention(q, k, v, causal=causal)
    out = _flash(q, k, v, causal, block_q, block_k)
    try:
        from jax.ad_checkpoint import checkpoint_name

        # Named for selective-remat policies (ISSUE 10): the 'dots'
        # policy saves this output alongside the MXU dot outputs. The
        # lse softmax residual lives INSIDE the custom_vjp, which jax's
        # remat treats atomically — a remat'd block re-runs the flash
        # forward for it regardless of policy (measured: one extra fwd
        # pallas_call in the remat'd backward jaxpr). Truly saving
        # "flash outputs + lse" therefore means NOT remat'ing — the
        # TPUFLOW_REMAT_POLICY=none mode, where the vjp residuals
        # (q, k, v, o, lse) are held from the forward and the backward
        # runs zero recompute.
        return checkpoint_name(out, "flash_out")
    except ImportError:  # very old jax: the name is advisory anyway
        return out
