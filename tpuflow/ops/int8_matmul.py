"""Fused native int8 matmul: the decode-path W8A8 contraction.

The measured failure this op exists to close (BENCH_r05, ROADMAP item
4): weight-only int8 decode ran **0.76x vs fp** at 124M/b8 because the
dequantize-into-matmul interceptor rebuilt bf16 weights per step —
convert + scale + write + read on top of the very matmul the int8 bytes
were supposed to shrink. The native path never materializes float
weights at all:

- activations are **dynamically quantized per row** (symmetric
  max-abs/127 over the contraction axis) at the matmul boundary;
- the contraction runs **int8 x int8 -> int32** — the form the MXU
  executes natively at 2x its bf16 rate on v5e, with integer (exact,
  width-independent) accumulation;
- the combined ``act_scale (x) weight_scale`` dequant is folded into the
  int32 -> float **epilogue**, one elementwise pass over the output.

Two implementations with BIT-IDENTICAL numerics (same rounding, same
clip, exact integer accumulation, same epilogue ops — pinned by
tests/test_int8_matmul.py):

- ``xla`` — ``lax.dot_general(int8, int8, preferred_element_type=int32)``
  plus an elementwise epilogue XLA fuses into the dot's output. Always
  available, every backend; XLA lowers the int8 dot to the MXU's native
  int8 path on TPU.
- ``pallas`` — one fused quantize-matmul-dequant kernel: the float
  activation tile quantizes to int8 *in VMEM* (the int8 copy never
  crosses HBM), the int32 accumulator lives in scratch across the K
  blocks, and the final K block applies the dequant epilogue before the
  single output write. This removes the quantize-op -> dot boundary XLA
  does not fuse across (the same fusion boundary that produced the
  0.76x dequant buffer, now on the activation side).

Dispatch mirrors ``tpuflow.ops.attention``'s flash thresholds:
``TPUFLOW_INT8_MATMUL`` forces ``xla`` | ``pallas`` (forced pallas runs
interpret-mode off-TPU, for tests); ``auto`` (default) picks pallas on
TPU when the shape tiles and the weight block is big enough for the
kernel to matter (``TPUFLOW_INT8_KERNEL_MIN_KN``, default K*N >= 2^18).
Untileable shapes — e.g. the 50257-column GPT-2 LM head — fall back to
the XLA path, which is still native int8 end to end.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from tpuflow.utils import knobs

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 spells it TPUCompilerParams (same alias as flash_attention).
    pltpu.CompilerParams = pltpu.TPUCompilerParams

# Kernel worth it once the streamed weight block dominates the launch:
# K*N below this (e.g. tiny test models) stays on the XLA path under
# 'auto'. Env override TPUFLOW_INT8_KERNEL_MIN_KN, like the flash
# min-seq knobs.
_DEFAULT_KERNEL_MIN_KN = 1 << 18
# One M block per kernel launch (decode M = batch/slot count, small):
# bound it so the f32 activation tile + int32 accumulator stay well
# inside VMEM. Bigger-M callers (training-width scoring forwards) take
# the XLA path under 'auto'.
_KERNEL_MAX_M = 1024

_warned_env: set[str] = set()
_warned_fallback: set[tuple[int, int, int]] = set()

# Programmatic impl override for a whole trace region (stronger than the
# env var, weaker than an explicit per-call impl=): QuantizedModel
# threads its ``int8_impl`` field through here so every int8 matmul a
# wrapper's apply traces — Dense interceptions AND the LM head deep in
# the model — resolves the same way. Trace-time state: the choice bakes
# into the compiled program, and because the field rides the hashable
# static model arg, two wrappers with different impls get different jit
# cache keys (the property the fused-vs-interceptor numerics tests
# stand on).
_IMPL_OVERRIDE: list = [None]


@contextlib.contextmanager
def impl_override(impl: str | None):
    """Scope an implementation choice over every ``int8_matmul`` call
    traced inside the region; ``None`` is a no-op."""
    if impl is None:
        yield
        return
    prev = _IMPL_OVERRIDE[0]
    _IMPL_OVERRIDE[0] = impl
    try:
        yield
    finally:
        _IMPL_OVERRIDE[0] = prev


def row_scales(x, scale_dtype=jnp.float32):
    """Per-row symmetric quantization scale over the LAST axis:
    max-abs/127, all-zero rows pinned to 1/127 (quantize to 0 instead of
    dividing by zero). The ONE scale formula shared by the XLA path, the
    Pallas path, and the Flax interceptor (tpuflow.infer.quant) — the
    bit-exactness contract between them starts here."""
    amax = jnp.max(jnp.abs(x.astype(scale_dtype)), axis=-1, keepdims=True)
    return jnp.where(amax > 0.0, amax, 1.0) / 127.0


def quantize_rows(x, scale_dtype=jnp.float32):
    """Dynamic per-row symmetric int8 quantization over the last axis.
    Returns ``(q int8, scale)`` with ``x ~= q * scale``. Round half to
    even (jnp.round), clip to [-127, 127] (symmetric — no -128, so
    negation is lossless)."""
    scale = row_scales(x, scale_dtype)
    q = jnp.clip(
        jnp.round(x.astype(scale_dtype) / scale), -127, 127
    ).astype(jnp.int8)
    return q, scale


def kernel_min_kn() -> int:
    raw = knobs.raw("TPUFLOW_INT8_KERNEL_MIN_KN")
    if not raw:
        return _DEFAULT_KERNEL_MIN_KN
    try:
        return max(int(raw), 0)
    except ValueError:
        if raw not in _warned_env:
            _warned_env.add(raw)
            print(
                f"[tpuflow] malformed TPUFLOW_INT8_KERNEL_MIN_KN={raw!r} "
                f"(want an integer); using {_DEFAULT_KERNEL_MIN_KN}"
            )
        return _DEFAULT_KERNEL_MIN_KN


def _pick_block(dim: int, candidates=(512, 256, 128)) -> int | None:
    """Largest MXU-friendly block evenly dividing ``dim`` (lane dim must
    stay a multiple of 128 for int8 tiles); None when ``dim`` doesn't
    tile — the caller falls back to the XLA path."""
    for b in candidates:
        if dim % b == 0:
            return b
    return None


def kernel_supported(m: int, k: int, n: int) -> bool:
    """Whether the fused kernel can run this shape at all (tiling only —
    the 'auto' profitability thresholds live in resolve_int8_impl)."""
    return (
        m >= 1 and _pick_block(k) is not None and _pick_block(n) is not None
    )


def resolve_int8_impl(
    m: int, k: int, n: int, *, backend: str | None = None
) -> str:
    """Dispatch for one (M, K, N) int8 matmul — factored out of
    ``int8_matmul`` so the choice is unit-testable without a TPU (the
    ``resolve_attention_impl`` idiom). ``TPUFLOW_INT8_MATMUL`` forces
    ``xla``/``pallas``; ``auto`` picks the fused kernel on TPU when the
    shape tiles, M fits one VMEM-resident block, and the weight block
    clears ``TPUFLOW_INT8_KERNEL_MIN_KN``. Resolved at trace time —
    baked into the compiled program per shape, like the flash
    thresholds."""
    env = (
        knobs.raw("TPUFLOW_INT8_MATMUL", "auto").strip().lower()
        or "auto"
    )
    if env in ("xla", "pallas"):
        return env
    if env != "auto" and env not in _warned_env:
        _warned_env.add(env)
        print(
            f"[tpuflow] unknown TPUFLOW_INT8_MATMUL={env!r} "
            "(want auto|xla|pallas); using auto"
        )
    backend = backend if backend is not None else jax.default_backend()
    if backend != "tpu":
        return "xla"
    if not kernel_supported(m, k, n):
        return "xla"
    if m < 8 or m > _KERNEL_MAX_M:
        return "xla"
    if k * n < kernel_min_kn():
        return "xla"
    return "pallas"


# ----------------------------------------------------------- pallas kernel
def _int8_matmul_kernel(
    x_ref, xs_ref, w_ref, ws_ref, o_ref, acc_scr, *, w_contract_last: bool
):
    """Fused quantize-matmul-dequant over one (M, block_n) output tile,
    K blocks sequential (grid dim 1): the float activation tile
    quantizes to int8 in VMEM with the precomputed per-row scale, the
    int8 x int8 dot accumulates exactly in the int32 scratch, and the
    last K block folds ``act_scale * weight_scale`` into the single
    float output write."""
    kb = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(kb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    s = xs_ref[:, :1]  # per-row activation scale (lane-broadcast input)
    xq = jnp.clip(
        jnp.round(x_ref[:].astype(jnp.float32) / s), -127, 127
    ).astype(jnp.int8)
    dims = (
        (((1,), (1,)), ((), ()))
        if w_contract_last
        else (((1,), (0,)), ((), ()))
    )
    acc_scr[:] += jax.lax.dot_general(
        xq, w_ref[:], dims, preferred_element_type=jnp.int32
    )

    @pl.when(kb == nk - 1)
    def _final():
        o_ref[:] = (
            acc_scr[:].astype(jnp.float32) * s * ws_ref[:1, :]
        ).astype(o_ref.dtype)


def _pallas_int8_matmul(
    x2d, wq, w_scale_row, *, w_contract_last: bool, out_dtype, interpret: bool
):
    m, k = x2d.shape
    n = wq.shape[0] if w_contract_last else wq.shape[1]
    bk = _pick_block(k)
    bn = _pick_block(n)
    # Scale computed OUTSIDE the kernel (it needs the whole row, which
    # spans every K block) — cheap VPU work XLA fuses; the int8 values
    # themselves never leave VMEM. Broadcast layouts follow the flash
    # lse convention: row-shaped operands ride a full 128-lane minor
    # dim, channel-shaped ones an 8-row sublane dim, for TPU tiling.
    s = row_scales(x2d)
    xs = jnp.broadcast_to(s, (m, 128))
    ws = jnp.broadcast_to(
        w_scale_row.reshape(1, n).astype(jnp.float32), (8, n)
    )
    if w_contract_last:
        w_spec = pl.BlockSpec((bn, bk), lambda j, kb: (j, kb))
    else:
        w_spec = pl.BlockSpec((bk, bn), lambda j, kb: (kb, j))
    return pl.pallas_call(
        functools.partial(
            _int8_matmul_kernel, w_contract_last=w_contract_last
        ),
        grid=(n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((m, bk), lambda j, kb: (0, kb)),
            pl.BlockSpec((m, 128), lambda j, kb: (0, 0)),
            w_spec,
            pl.BlockSpec((8, bn), lambda j, kb: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda j, kb: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.int32)],
        # Output tiles are independent; the K loop is a sequential
        # reduction carrying the int32 accumulator in scratch.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(x2d, xs, wq, ws)


def _xla_int8_matmul(x2d, wq, w_scale_row, *, w_contract_last: bool,
                     out_dtype):
    """The always-available reference path: same quantization, same
    integer accumulation, same epilogue op order as the kernel — the two
    are bit-identical (integer adds are associative, the float epilogue
    is elementwise), pinned by tests/test_int8_matmul.py."""
    xq, s = quantize_rows(x2d)
    dims = (
        (((1,), (1,)), ((), ()))
        if w_contract_last
        else (((1,), (0,)), ((), ()))
    )
    acc = jax.lax.dot_general(
        xq, wq, dims, preferred_element_type=jnp.int32
    )
    out = (
        acc.astype(jnp.float32)
        * s
        * w_scale_row.reshape(1, -1).astype(jnp.float32)
    )
    return out.astype(out_dtype)


def int8_matmul(
    x,
    wq,
    w_scale,
    *,
    w_contract_last: bool = False,
    out_dtype=jnp.float32,
    impl: str | None = None,
):
    """``x (..., K) float @ wq int8 -> (..., N) out_dtype`` with dynamic
    per-row activation quantization and the dequant epilogue fused in.

    ``wq`` is ``(K, N)`` — a Dense kernel — or ``(N, K)`` with
    ``w_contract_last=True`` (the LM-head layout: GPT-2's tied ``wte``
    is ``(vocab, n_embd)``; contracting its LAST axis avoids ever
    materializing a transposed int8 copy). ``w_scale`` holds the
    per-out-channel weight scales, any shape of size N (or a single
    per-tensor scale). ``impl`` overrides the dispatch
    (``resolve_int8_impl``); a forced ``pallas`` on an untileable shape
    falls back to the XLA path (numerics identical) with a
    ``quant.kernel_fallback`` event.
    """
    if wq.dtype != jnp.int8:
        raise TypeError(f"wq must be int8, got {wq.dtype}")
    if wq.ndim != 2:
        raise ValueError(f"wq must be 2-D, got shape {wq.shape}")
    k = x.shape[-1]
    n, kw = wq.shape if w_contract_last else wq.shape[::-1]
    if kw != k:
        raise ValueError(
            f"contraction mismatch: x (..., {k}) vs wq {wq.shape} "
            f"(w_contract_last={w_contract_last})"
        )
    w_scale = jnp.asarray(w_scale)
    if w_scale.size == 1:
        w_scale_row = jnp.broadcast_to(w_scale.reshape(()), (n,))
    elif w_scale.size == n:
        w_scale_row = w_scale.reshape(n)
    else:
        raise ValueError(
            f"w_scale has {w_scale.size} elements; want {n} "
            "(per-out-channel) or 1 (per-tensor)"
        )
    lead = x.shape[:-1]
    m = math.prod(lead) if lead else 1
    x2d = x.reshape(m, k)
    if impl in (None, "auto"):
        impl = _IMPL_OVERRIDE[0]
    chosen = impl if impl not in (None, "auto") else resolve_int8_impl(m, k, n)
    if chosen not in ("xla", "pallas"):
        raise ValueError(f"unknown int8 impl {chosen!r}; use xla|pallas")
    if chosen == "pallas" and not kernel_supported(m, k, n):
        shape = (m, k, n)
        if shape not in _warned_fallback:
            _warned_fallback.add(shape)
            from tpuflow import obs

            obs.event(
                "quant.kernel_fallback", m=m, k=k, n=n,
                reason="shape does not tile (K/N % 128)",
            )
        chosen = "xla"
    if chosen == "pallas":
        out = _pallas_int8_matmul(
            x2d, wq, w_scale_row,
            w_contract_last=w_contract_last, out_dtype=out_dtype,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        out = _xla_int8_matmul(
            x2d, wq, w_scale_row,
            w_contract_last=w_contract_last, out_dtype=out_dtype,
        )
    return out.reshape(*lead, n)
