"""Parallelism layouts over the named mesh: FSDP, tensor, sequence (ring),
pipeline (GPipe over 'stage')."""

from tpuflow.parallel.pipeline import (
    gpt2_pipeline_loss,
    gpt2_pipeline_shardings,
    make_pipeline_loss,
)
from tpuflow.parallel.sharding import (
    create_sharded_state,
    gpt2_tensor_rules,
    has_sharded_leaf,
    make_shardings,
)
from tpuflow.parallel.ulysses import ulysses_attention

__all__ = [
    "create_sharded_state",
    "gpt2_tensor_rules",
    "has_sharded_leaf",
    "make_shardings",
    "make_pipeline_loss",
    "gpt2_pipeline_loss",
    "gpt2_pipeline_shardings",
    "ulysses_attention",
]
