"""Parallelism layouts over the named mesh: FSDP, tensor, sequence (ring)."""

from tpuflow.parallel.sharding import (
    create_sharded_state,
    gpt2_tensor_rules,
    make_shardings,
)

__all__ = ["create_sharded_state", "gpt2_tensor_rules", "make_shardings"]
