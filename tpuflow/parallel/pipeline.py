"""Pipeline parallelism: GPipe-style microbatch pipeline over a 'stage' mesh
axis, TPU-idiomatic (shard_map + lax.ppermute over ICI neighbors).

The reference exercises no pipeline parallelism (its model is a 3-layer MLP,
my_ray_module.py:94-112; SURVEY.md §2c marks PP absent) — this exists so the
framework's parallelism matrix (dp/fsdp/tp/sp/ep/**pp**) is complete and the
mesh design demonstrably does not preclude it.

Design (SPMD, compiler-friendly):

- The model's repeated blocks are stacked along a leading layer axis (the
  ``scan_layers=True`` parameter layout of ``tpuflow.models.gpt2.GPT2``) and
  sharded over ``stage``: each stage owns ``n_layer / n_stages`` contiguous
  layers. Nothing is "sent" at schedule time except activations.
- The batch is split into M microbatches. One ``lax.scan`` runs
  ``M + S - 1`` ticks; at each tick every stage applies its layer slice to
  its current activation and passes the result to the next stage with a
  single ``lax.ppermute`` — nearest-neighbor ICI traffic, no host logic, no
  dynamic shapes. The first/last ticks are the classic GPipe bubble; their
  garbage activations are masked out of the loss.
- The embedding runs where stage 0 ingests a microbatch and the loss head
  where the last stage emits one; under SPMD every device executes both and
  a ``where(stage_id == ...)`` selects the real value (the textbook
  single-program pipeline; the redundant compute is bubble-shaped and small
  next to the block stack for deep models).
- ``jax.grad`` differentiates straight through: the transpose of
  ``ppermute`` is the reverse permute, so the backward schedule is the
  mirrored pipeline — no hand-written backward pass.

Composes with data parallelism: run on a ``{'data': D, 'stage': S}`` mesh —
the batch shards over 'data', losses psum over both axes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.5: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        kw["check_rep"] = kw.pop("check_vma", True)
        return _shard_map_old(f, **kw)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_STAGE = "stage"


def make_pipeline_loss(
    block_apply: Callable[[Any, jax.Array], tuple[jax.Array, jax.Array]],
    embed: Callable[[Any, jax.Array], jax.Array],
    head_loss: Callable[[Any, jax.Array, jax.Array], jax.Array],
    *,
    mesh: Mesh,
    n_microbatches: int,
    data_axis: str = "data",
) -> Callable[[Any, Any, jax.Array, jax.Array], jax.Array]:
    """Build ``loss(stacked_block_params, other_params, tokens, targets)``.

    - ``block_apply(block_params, x) -> (x, aux)``: one repeated block,
      given one layer's params (a slice of the stacked tree along its
      leading axis), plus a scalar auxiliary loss (0.0 for plain blocks;
      the sown MoE load-balance term for expert blocks).
    - ``embed(other_params, tokens) -> x``: the stage-0 ingress computation.
    - ``head_loss(other_params, x, targets) -> scalar``: the last-stage
      egress computation (mean loss over the microbatch's tokens).

    Auxiliary losses are accumulated per stage only at VALID ticks (stage
    ``s`` processes real microbatch data at ticks ``t in [s, s+M)``; bubble
    and re-ingested activations are masked out), psum'd across stages, and
    averaged over microbatches — the per-microbatch analogue of the
    non-pipelined step's sown-loss sum (train/step.py:101-103).

    The returned callable is jit-compatible and differentiable; its result
    is the mean loss over all microbatches, replicated on every device.
    """
    S = mesh.shape[AXIS_STAGE]
    D = mesh.shape.get(data_axis, 1)
    M = n_microbatches
    if S < 2:
        raise ValueError(f"pipeline needs >=2 stages, mesh has {S}")

    def spmd(blocks_local, other, tokens, targets):
        # blocks_local: this stage's (n_layer/S, ...) slice of every leaf.
        # tokens/targets: this data-shard's (B/D, T) slice.
        sid = jax.lax.axis_index(AXIS_STAGE)
        Bd, T = tokens.shape
        if Bd % M:
            raise ValueError(f"per-data-shard batch {Bd} not divisible by M={M}")
        x_mb = tokens.reshape(M, Bd // M, T)
        y_mb = targets.reshape(M, Bd // M, T)

        def apply_stage(x):
            out, auxs = jax.lax.scan(
                lambda h, lp: block_apply(lp, h), x, blocks_local
            )
            return out, jnp.sum(auxs)

        # Shape/dtype of the inter-stage activation buffer.
        probe = jax.eval_shape(lambda t: embed(other, t), x_mb[0])
        state0 = jnp.zeros(probe.shape, probe.dtype)

        right = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            state, loss_acc, aux_acc = carry
            # Stage 0 ingests microbatch t while ingress ticks remain.
            ingress = embed(other, x_mb[jnp.clip(t, 0, M - 1)])
            x = jnp.where(sid == 0, ingress, state)
            y, aux = apply_stage(x)
            # This stage holds REAL data exactly at ticks [sid, sid+M):
            # before that, bubble zeros; after, re-ingested/stale input.
            stage_valid = (t >= sid) & (t < sid + M)
            aux_acc = aux_acc + jnp.where(stage_valid, aux, 0.0)
            # Last stage emits microbatch t-(S-1) once the pipe has filled.
            emit_t = jnp.clip(t - (S - 1), 0, M - 1)
            mb_loss = head_loss(other, y, y_mb[emit_t])
            valid = (sid == S - 1) & (t >= S - 1)
            loss_acc = loss_acc + jnp.where(valid, mb_loss, 0.0)
            # Hand activations to the right neighbor (ICI nearest-neighbor);
            # stage S-1's output leaves the pipe (no wraparound edge).
            state = jax.lax.ppermute(y, AXIS_STAGE, right)
            return (state, loss_acc, aux_acc), None

        # Accumulators are rank-1 ((1,) not scalar): device-varying rank-0
        # residuals of the scan can't be concatenated by shard_map's grad
        # machinery on older jax (_check_names rejects names on a rank-0
        # aval) — the singleton axis costs nothing and transposes cleanly
        # everywhere.
        (_, loss_acc, aux_acc), _ = jax.lax.scan(
            tick,
            (
                state0,
                jnp.zeros((1,), jnp.float32),
                jnp.zeros((1,), jnp.float32),
            ),
            jnp.arange(M + S - 1),
        )
        # Only the last stage accumulated task losses; every stage holds
        # its own layers' aux. Each shard emits its CONTRIBUTION as one
        # cell of an (S, D) grid; the replicated global mean is taken
        # OUTSIDE the shard_map (sum over a sharded array is an ordinary
        # XLA reduction) — device-varying out_specs transpose cleanly
        # under grad on every jax version, where an in-body psum to a
        # replicated P() output trips old shard_map's rep tracking.
        return (loss_acc + aux_acc).reshape(1, 1)

    def loss_fn(stacked_blocks, other, tokens, targets):
        # check_vma=False: the scan carries (activation buffer, loss
        # accumulator) start as replicated zeros and become device-varying
        # on the first tick — intended here, the contributions grid out
        # spec declares the output varying.
        f = shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(AXIS_STAGE), P(), P(data_axis), P(data_axis)),
            out_specs=P(AXIS_STAGE, data_axis),
            check_vma=False,
        )
        contrib = f(stacked_blocks, other, tokens, targets)  # (S, D)
        # Mean over microbatches and data shards (the stage dimension is a
        # sum by construction: only valid cells accumulated anything).
        return jnp.sum(contrib) / (D * M)

    return loss_fn


# --------------------------------------------------------- GPT-2 adapter
def gpt2_pipeline_loss(
    config,
    *,
    mesh: Mesh,
    n_microbatches: int,
) -> Callable[[Any, jax.Array, jax.Array], jax.Array]:
    """Pipeline-parallel LM loss for ``GPT2(config, scan_layers=True)``
    params (the stacked-block layout), split as embed | blocks | ln_f+head.

    Dropout is off (inference-mode blocks): pipeline training runs the
    deterministic path, matching ``train=False`` semantics. Params keep the
    exact GPT2 pytree, so checkpoints interchange with the non-pipelined
    scan model. Cites the non-pipelined loss (train/step.py:89-105) as the
    numerical reference; ``tests/test_pipeline.py`` asserts equivalence.
    """
    from tpuflow.models.gpt2 import Block
    from tpuflow.models.losses import cross_entropy_loss

    cfg = config
    if not cfg.scan_layers:
        raise ValueError("pipeline params require GPT2Config(scan_layers=True)")
    if cfg.n_layer % mesh.shape[AXIS_STAGE]:
        raise ValueError(
            f"n_layer={cfg.n_layer} not divisible by "
            f"stage={mesh.shape[AXIS_STAGE]}"
        )
    block = Block(cfg)

    if cfg.n_experts > 0:
        # MoE blocks sow their load-balance aux into 'losses'; collect it
        # per layer so the schedule can mask/accumulate it (pipeline × EP
        # composition). Note the per-microbatch aux is computed on B/M rows
        # — the GPipe analogue of the full-batch statistic, equal up to
        # microbatch routing covariance.
        from tpuflow.models.losses import sum_sown_losses

        def block_apply(layer_params, x):
            out, updates = block.apply(
                {"params": layer_params}, x, False, mutable=["losses"]
            )
            return out, sum_sown_losses(updates)
    else:

        def block_apply(layer_params, x):
            return block.apply({"params": layer_params}, x, False), jnp.float32(0.0)

    def embed(other, tokens):
        T = tokens.shape[1]
        x = other["wte"][tokens].astype(cfg.dtype)
        return x + other["wpe"][:T].astype(cfg.dtype)

    def head_loss(other, x, targets):
        import flax.linen as nn

        x = nn.LayerNorm(dtype=cfg.dtype).apply({"params": other["ln_f"]}, x)
        logits = jnp.einsum(
            "btc,vc->btv", x, other["wte"].astype(cfg.dtype)
        ).astype(jnp.float32)
        # The canonical LM loss — same helper as the non-pipelined step.
        return cross_entropy_loss(logits, targets)

    pipe = make_pipeline_loss(
        block_apply, embed, head_loss, mesh=mesh, n_microbatches=n_microbatches
    )

    def loss_fn(params, tokens, targets):
        other = {k: v for k, v in params.items() if k != "h"}
        return pipe(params["h"]["block"], other, tokens, targets)

    return loss_fn


def gpt2_pipeline_shardings(mesh: Mesh, params):
    """NamedShardings for a GPT2 scan-layout param tree under pipeline
    parallelism: the stacked blocks split over 'stage', the rest replicated."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(
            mesh,
            P(AXIS_STAGE)
            if any(getattr(k, "key", None) == "h" for k in path)
            else P(),
        ),
        params,
    )
