"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

Long-context strategy (first-class per the framework mandate; absent from the
reference, which has no attention — SURVEY.md §5): the sequence dimension of
q/k/v is sharded over 'seq'. Each shard keeps its Q block resident and
computes blockwise (online-softmax) attention against the KV block it
currently holds, then rotates KV around the ring with
``jax.lax.ppermute`` — after ``seq`` steps every Q block has seen every KV
block, with peak memory O(T/shards) per device and the permute riding
nearest-neighbor ICI links. Causality is applied from global block offsets;
the update math matches tpuflow.ops.blockwise_attention exactly, so ring
output equals single-device attention bit-for-near-bit.

Differentiable end-to-end (pure jnp + ppermute inside shard_map), so it
drops into the training step as ``attn_impl='ring'`` on GPT2Config.
"""

from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpuflow.dist import AXIS_DATA, AXIS_FSDP, AXIS_SEQ

_NEG_INF = -1e30


def _ring_shard_fn(q, k, v, *, causal: bool, axis_name: str):
    """Per-shard body (inside shard_map). q,k,v: (B, T_local, H, D)."""
    B, Tl, H, D = q.shape
    size = (
        jax.lax.axis_size(axis_name)
        if hasattr(jax.lax, "axis_size")
        # jax < 0.5 idiom: psum of the unit constant folds to the static
        # axis size at trace time.
        else jax.lax.psum(1, axis_name)
    )
    my_idx = jax.lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    q32 = q.astype(jnp.float32)
    q_pos = my_idx * Tl + jnp.arange(Tl)

    def body(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src_idx = (my_idx - step) % size  # whose KV block we hold now
        k_pos = src_idx * Tl + jnp.arange(Tl)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32)
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        # Rotate KV to the next ring neighbor (nearest-neighbor ICI hop).
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((B, H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v), jnp.arange(size)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _current_mesh():
    # Supported context first: jax.set_mesh(mesh) / jax.sharding.get_mesh().
    # Under an active jit trace get_mesh() refuses to run; the abstract mesh
    # carries the axis structure and shard_map accepts it (devices are bound
    # at lowering from the set_mesh context).
    if hasattr(jax.sharding, "get_mesh"):
        try:
            mesh = jax.sharding.get_mesh()
            if not getattr(mesh, "empty", True):
                return mesh
        except ValueError:
            mesh = jax.sharding.get_abstract_mesh()
            if not getattr(mesh, "empty", True):
                return mesh
    # Legacy `with mesh:` context: thread_resources via its public
    # deprecation-path alias (not jax._src). Tolerate removal in a future
    # JAX: the helpful error below still fires.
    try:
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from jax.interpreters.pxla import thread_resources

        mesh = thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        mesh = None
    if mesh is None or mesh.empty:
        raise RuntimeError(
            "ring_attention needs an active mesh: run under `with mesh:` or "
            "`jax.set_mesh(mesh)` (Trainer.fit does this automatically), or "
            "pass mesh= explicitly"
        )
    return mesh


def ring_attention(q, k, v, *, causal: bool = True, axis_name: str = AXIS_SEQ,
                   mesh=None):
    """Sequence-parallel attention. q,k,v: (B, T, H, D) with T sharded over
    ``axis_name``; output sharded the same way. Requires T % seq_shards == 0.
    With a trivial 'seq' axis (size 1) this degrades to blockwise attention
    in one shard — same math, no communication.
    """
    mesh = mesh if mesh is not None else _current_mesh()
    seq_shards = mesh.shape.get(axis_name, 1)
    if seq_shards == 1 or q.shape[1] % seq_shards:
        # Trivial ring, or T not divisible by the ring size: same math with
        # no rotation — blockwise attention (GSPMD lays it out from the
        # ambient shardings). Defined behavior instead of a shard_map error.
        from tpuflow.ops.flash_attention import blockwise_attention

        return blockwise_attention(q, k, v, causal=causal)
    return seq_shard_map(
        lambda q, k, v: _ring_shard_fn(
            q, k, v, causal=causal, axis_name=axis_name
        ),
        mesh,
        axis_name,
        batch=q.shape[0],
    )(q, k, v)


def seq_shard_map(body, mesh, axis_name, *, batch: int):
    """shard_map wrapper shared by the sequence-parallel attentions (ring and
    ulysses): batch dim over the data-like axes when it divides, sequence dim
    over ``axis_name``, heads/head_dim replicated. ``check_vma=False`` —
    the bodies' carries/collectives manage their own device variance."""
    batch_axes = tuple(
        a for a in (AXIS_DATA, AXIS_FSDP) if mesh.shape.get(a, 1) > 1
    )
    batch_size = (
        int(np.prod([mesh.shape[a] for a in batch_axes])) if batch_axes else 1
    )
    if batch_axes and batch % batch_size != 0:
        batch_axes = ()  # e.g. model.init traces with batch 1: replicate it
    spec = P(batch_axes if batch_axes else None, axis_name, None, None)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    # jax < 0.5: shard_map lives under experimental and the variance check
    # flag is spelled check_rep.
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
