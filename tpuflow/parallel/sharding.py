"""Parameter/optimizer sharding rules: DP, FSDP, tensor parallelism.

The reference's only strategy is DDP (replicated params, sharded batch —
my_ray_module.py:135); its acceptance configs add "FSDP → pjit fully-sharded"
(BASELINE.md config 5). Here both are *layouts on the same named mesh*, not
wrappers:

- **DP**: params replicated, batch on ('data','fsdp') — the default of
  tpuflow.dist.
- **FSDP / ZeRO-3**: every param (and its mirrored optimizer moments) sharded
  along its largest divisible dimension over the fsdp(+data) axes; XLA GSPMD
  inserts the all-gathers before use and reduce-scatters for grads.
- **Tensor parallel**: per-layer PartitionSpecs over the 'tensor' axis
  (Megatron-style column/row splits for GPT-2 blocks), composable with FSDP.

Shardings are computed *by leaf path and shape* over the abstract TrainState,
so optimizer state (whose leaves mirror param shapes and paths) is sharded
consistently without optimizer-specific code. ``create_sharded_state`` jits
the init with ``out_shardings`` so parameters are **born sharded** — no
single-host materialization, which is what makes multi-host GPT-2-medium
init and the sharded-checkpoint path work.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuflow.dist import AXIS_DATA, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR


def _path_names(path) -> tuple[str, ...]:
    names = []
    for entry in path:
        if hasattr(entry, "key"):
            names.append(str(entry.key))
        elif hasattr(entry, "name"):
            names.append(str(entry.name))
        elif hasattr(entry, "idx"):
            names.append(str(entry.idx))
    return tuple(names)


def gpt2_tensor_rules(names: tuple[str, ...], shape: tuple[int, ...]):
    """Megatron-style tensor-parallel placements for GPT-2 params (and their
    mirrored optimizer moments — paths contain the same layer names).

    Column-parallel (shard output dim): c_attn qkv, mlp_fc.
    Row-parallel (shard input dim): c_proj, mlp_proj.
    Embeddings: vocab dim sharded. LayerNorms/biases: replicated.
    """
    if not names or len(shape) == 0:
        return None
    leaf = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    # Dense kernels are (in, out) — or (layers, in, out) when the layer stack
    # is nn.scan'd (GPT2Config.scan_layers): the split dim shifts right.
    if leaf == "kernel" and len(shape) in (2, 3):
        col, row = len(shape) - 1, len(shape) - 2
        if parent in ("c_attn", "mlp_fc"):
            return {col: AXIS_TENSOR}  # column parallel
        if parent in ("c_proj", "mlp_proj"):
            return {row: AXIS_TENSOR}  # row parallel
    if leaf in ("wte", "wpe") and len(shape) == 2:
        return {0: AXIS_TENSOR}
    # MoE expert stacks: w1 (E, C, F) / w2 (E, F, C) — or with a scanned
    # layer stack (L, E, ...). Experts shard over 'expert'; the FFN dim also
    # splits over 'tensor' (column for w1, row for w2), composing EP × TP.
    if parent == "moe" and leaf in ("w1", "w2") and len(shape) in (3, 4):
        expert_dim = len(shape) - 3
        placed = {expert_dim: AXIS_EXPERT}
        placed[len(shape) - 1 if leaf == "w1" else len(shape) - 2] = AXIS_TENSOR
        return placed
    if parent == "moe" and leaf in ("b1", "b2") and len(shape) in (2, 3):
        return {len(shape) - 2: AXIS_EXPERT}
    return None


def make_shardings(
    abstract_tree,
    mesh: Mesh,
    *,
    fsdp: bool = True,
    tensor_rules: Callable | None = None,
    min_shard_elems: int = 2**12,
):
    """Compute a NamedSharding per leaf of ``abstract_tree``.

    Per leaf: apply ``tensor_rules`` (dim → 'tensor' axis) first, then — if
    ``fsdp`` — shard the largest remaining dimension divisible by the fsdp
    world over ('fsdp','data'). Small leaves (< ``min_shard_elems``) and
    scalars stay replicated: gathering tiny tensors costs more than storing
    them.
    """
    fsdp_axes = tuple(a for a in (AXIS_FSDP, AXIS_DATA) if mesh.shape.get(a, 1) > 1)
    fsdp_size = int(np.prod([mesh.shape[a] for a in fsdp_axes])) if fsdp_axes else 1

    def _axis_size(axis) -> int:
        names = axis if isinstance(axis, tuple) else (axis,)
        return int(np.prod([mesh.shape.get(a, 1) for a in names]))

    def one(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        spec: list = [None] * len(shape)
        if shape and int(np.prod(shape)) >= min_shard_elems:
            names = _path_names(path)
            placed = tensor_rules(names, shape) if tensor_rules else None
            if placed:
                # Each rule names its own mesh axis ('tensor', 'expert', …);
                # apply it when that axis exists non-trivially and divides.
                for dim, axis in placed.items():
                    size = _axis_size(axis)
                    if size > 1 and shape[dim] % size == 0:
                        spec[dim] = axis
            if fsdp and fsdp_size > 1:
                # Largest free dim divisible by the fsdp world.
                candidates = [
                    (shape[d], d)
                    for d in range(len(shape))
                    if spec[d] is None and shape[d] % fsdp_size == 0
                ]
                if candidates:
                    _, dim = max(candidates)
                    spec[dim] = fsdp_axes if len(fsdp_axes) > 1 else fsdp_axes[0]
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(one, abstract_tree)


def has_sharded_leaf(shardings, axis: str | None = None) -> bool:
    """True if any leaf of a shardings pytree is actually partitioned
    (optionally: on the named ``axis``). Guards equivalence checks that
    would pass vacuously if a rules/threshold regression silently returned
    fully replicated shardings (used by tests and the multichip dryrun)."""
    for s in jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    ):
        for part in s.spec:
            if part is None:
                continue
            if axis is None:
                return True
            names = part if isinstance(part, tuple) else (part,)
            if axis in names:
                return True
    return False


def create_sharded_state(
    init_fn: Callable,
    mesh: Mesh,
    *init_args,
    fsdp: bool = True,
    tensor_rules: Callable | None = None,
    materialize: bool = True,
):
    """Initialize a TrainState (or any pytree) *born sharded*.

    ``init_fn(*init_args)`` is evaluated abstractly to compute per-leaf
    shardings, then jitted with those as ``out_shardings`` — each device
    materializes only its shard (the pjit initialization idiom; no
    host-memory spike for GPT-2-medium-sized states).

    Returns (state, shardings). With ``materialize=False`` the init is
    ONLY shape-evaluated — ``state`` is the abstract pytree
    (ShapeDtypeStructs carrying their shardings, so it serves directly
    as a restore template) and nothing executes on devices. Checkpoint
    resumes use this: running the real initializer just to overwrite
    every leaf with restored values doubles startup for nothing (a
    355M-param init materializes ~4 GiB of random weights + zeroed adamw
    moments that the restore immediately discards).
    """
    abstract = jax.eval_shape(init_fn, *init_args)
    shardings = make_shardings(
        abstract, mesh, fsdp=fsdp, tensor_rules=tensor_rules
    )
    if not materialize:
        abstract = jax.tree_util.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            abstract,
            shardings,
        )
        return abstract, shardings
    state = jax.jit(init_fn, out_shardings=shardings)(*init_args)
    return state, shardings
