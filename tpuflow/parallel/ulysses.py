"""Ulysses-style sequence parallelism: all-to-all head↔sequence swap.

The second long-context strategy next to ring attention (SURVEY.md §5;
the task mandates "ring attention or all-to-all sequence/context
parallelism" — tpuflow ships both, selectable per model via
``attn_impl='ring' | 'ulysses'``):

- Activations arrive sequence-sharded over the 'seq' mesh axis:
  each shard holds (B, T/s, H, D).
- One ``lax.all_to_all`` swaps the sharded dimension: split the HEADS
  across the axis and concatenate the sequence — every shard now holds
  (B, T, H/s, D), i.e. the FULL sequence for a subset of heads.
- Attention runs locally with ordinary causal masking (no cross-shard
  softmax state at all — the advantage over ring for moderate contexts),
  through any inner implementation (XLA einsum or the Pallas flash
  kernel).
- A second all-to-all swaps the output back to sequence sharding.

Communication: 4 all-to-alls of one activation each per call (q, k, v in;
output back) vs ring's s-step KV rotation; all ride ICI. Differentiable
end-to-end (all_to_all transposes to all_to_all), so it drops into the
training step as ``attn_impl='ulysses'`` on GPT2Config.
"""

from __future__ import annotations

import jax

from tpuflow.dist import AXIS_SEQ


def _ulysses_shard_fn(q, k, v, *, causal: bool, axis_name: str,
                      inner_impl: str):
    """Per-shard body. q,k,v local: (B, T/s, H, D)."""
    from tpuflow.ops.attention import attention

    def to_heads(x):  # (B, T/s, H, D) -> (B, T, H/s, D)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def to_seq(x):  # (B, T, H/s, D) -> (B, T/s, H, D)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = attention(
        to_heads(q), to_heads(k), to_heads(v), causal=causal, impl=inner_impl
    )
    return to_seq(out)


def ulysses_attention(
    q, k, v, *, causal: bool = True, axis_name: str = AXIS_SEQ, mesh=None,
    inner_impl: str = "xla",
):
    """Sequence-parallel attention via head↔seq all-to-all. q,k,v:
    (B, T, H, D) with T sharded over ``axis_name``; output sharded the same
    way. Needs T and H divisible by the axis size; otherwise (or with a
    trivial axis) falls back to blockwise attention — same math, no
    communication, defined behavior instead of a shard_map error.

    ``inner_impl`` selects the per-shard attention ('xla' or 'flash' — the
    Pallas kernel composes, since each shard sees an ordinary full-sequence
    attention over its head subset; the sequence-parallel impls would
    re-enter shard_map and are rejected).
    """
    from tpuflow.parallel.ring_attention import _current_mesh, seq_shard_map

    if inner_impl not in ("xla", "flash"):
        raise ValueError(
            f"inner_impl must be 'xla' or 'flash', got {inner_impl!r} "
            "(sequence-parallel impls cannot nest inside ulysses)"
        )
    mesh = mesh if mesh is not None else _current_mesh()
    s = mesh.shape.get(axis_name, 1)
    B, T, H, D = q.shape
    if s == 1 or T % s or H % s:
        from tpuflow.ops.flash_attention import blockwise_attention

        return blockwise_attention(q, k, v, causal=causal)
    return seq_shard_map(
        lambda q, k, v: _ulysses_shard_fn(
            q, k, v, causal=causal, axis_name=axis_name, inner_impl=inner_impl
        ),
        mesh,
        axis_name,
        batch=B,
    )(q, k, v)
