"""Test-support subpackage: fault injection for the failure paths.

``tpuflow.testing.faults`` is shipped (not test-only) because the hooks
must live inside the production code paths they exercise — gang exec, the
train loops, the raw checkpoint saver — and activate purely from the
``TPUFLOW_FAULT`` environment variable, so chaos tests drive real
subprocess gangs with no monkeypatching across process boundaries.
"""

from tpuflow.testing import faults

__all__ = ["faults"]
