"""In-process serving-chaos harness (ISSUE 17).

The proof vehicle for the front-door router's fault-tolerance claims:
several REAL replicas — each a live ServeEngine with its own
ReplicaGateway (/generate), its own ProcessLedger, and its own
MetricsServer (/status) — run inside one process, discovered through a
real registration dir and polled by a real FleetObservatory over real
HTTP. Chaos is injected through the PR 6 fault vocabulary
(``replica_kill:<id>@<t>`` / ``replica_stall:<id>@<t>`` /
``prefill_kill:<id>@<t>`` in ``TPUFLOW_FAULT``, read via
``faults.replica_plan()``) or directly via ``LocalReplica.kill()`` /
``.stall()`` / ``.drain()``.

Per-replica state stays private on purpose: the engines would
otherwise all feed the process-singleton goodput ledger and the fleet
would see one smeared replica instead of three distinct ones.

``kill()`` models a dead pod: the step loop stops, every held and new
/generate answers 503 "killed" immediately, and both servers close so
the observatory's next poll fails → the row goes stale. ``stall()``
models a wedged device: sockets stay open and accepting, nothing ever
finishes — the failure mode only a forward timeout can detect.
``drain()`` models SIGTERM: in-flight work finishes (admit=False
stepping), queued-but-unstarted work is terminal-traced ``drained`` so
the gateway 503s it back to the router for re-dispatch.

``run_poisson`` is the load side: open-loop Poisson arrivals, one
submitter thread per request, everything accounted — a request ends as
exactly one of ok / rejected (explicit 503) / error, and the chaos
tests assert the error bucket is empty and every ok answer is
bit-equal to a solo ``generate()``.
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from typing import Any, Callable

import numpy as np

from tpuflow.infer.frontdoor import ReplicaGateway
from tpuflow.infer.router import FleetBusy
from tpuflow.obs import fleet as obs_fleet
from tpuflow.obs.export import MetricsServer
from tpuflow.obs.goodput import ProcessLedger


class LocalReplica:
    """One in-process serving replica: engine + gateway + /status.

    The caller builds (and warms) the engine — warmups must happen
    serially BEFORE chaos starts, both because compiles are the
    expensive part and because the never-recompile check needs a clean
    post-warmup baseline. ``device_lock`` serializes device work across
    replicas sharing one physical device (the CPU test topology); pass
    None when each replica owns its device.
    """

    def __init__(
        self,
        replica_id: str,
        engine: Any,
        *,
        registration_dir: str | None = None,
        device_lock: threading.Lock | None = None,
        idle_sleep_s: float = 0.002,
    ):
        self.id = str(replica_id)
        self.engine = engine
        self.lock = threading.RLock()
        self._device_lock = device_lock
        self._idle_sleep_s = float(idle_sleep_s)
        self._ledger = ProcessLedger()
        self._ledger.note_serve_state(0, 0, engine.max_slots)
        # Disaggregated serving (ISSUE 19): the fleet row carries this
        # replica's role so the router can split ship hops from decode
        # placement, and its spill-tier page counts for the warmth
        # tie-break.
        self._ledger.note_serve_role(getattr(engine, "role", "both"))
        if getattr(engine, "pool", None) is not None:
            self._ledger.note_serve_pages(
                engine.pool.free_pages, engine.pool.usable_pages
            )
        self.gateway = ReplicaGateway(
            engine, lock=self.lock, on_complete=self._completed
        )
        self.metrics = MetricsServer(0, snapshot_fn=self._status)
        if registration_dir:
            obs_fleet.register_replica(
                registration_dir,
                self.metrics.url,
                identity={"id": self.id},
            )
        self._stop = threading.Event()
        self._stalled = threading.Event()
        self._draining = False
        self._drained_queue = False
        self._thread = threading.Thread(
            target=self._loop,
            name=f"tpuflow-replica-{self.id}",
            daemon=True,
        )
        self._thread.start()

    # -------------------------------------------------------- plumbing
    def _completed(self, handle: Any) -> None:
        """Feed the private ledger per finished request: the TTFT
        histogram is what makes the fleet's MERGED p99 exist. Traced
        requests pin their trace id as the bucket exemplar (ISSUE 18)
        — but only when the trace is actually recorded, so a fleet p99
        exemplar always resolves to spans on disk."""
        ctx = getattr(handle, "trace_ctx", None)
        self._ledger.note_serve_ttft(
            getattr(handle, "ttft_s", None),
            trace_id=(
                ctx.trace_id
                if ctx is not None and ctx.recorded
                else None
            ),
        )
        self._ledger.note_serve_complete()

    def _status(self) -> dict:
        return {
            **self._ledger.snapshot(),
            "replica": {"id": self.id},
            "generate_url": self.gateway.url,
        }

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._stalled.is_set():
                time.sleep(self._idle_sleep_s)
                continue
            did = False
            dev = self._device_lock or nullcontext()
            with dev:
                with self.lock:
                    eng = self.engine
                    if eng.queue_depth > 0 or eng.live_slots > 0:
                        did = eng.step(admit=not self._draining)
                    if (
                        self._draining
                        and not self._drained_queue
                        and eng.live_slots == 0
                    ):
                        # SIGTERM path: in-flight work finished; hand
                        # queued-but-unstarted work back to the router.
                        eng.drain_queued()
                        self._drained_queue = True
                    self._ledger.note_serve_state(
                        eng.queue_depth, eng.live_slots, eng.max_slots
                    )
                    if getattr(eng, "pool", None) is not None:
                        self._ledger.note_serve_pages(
                            eng.pool.free_pages, eng.pool.usable_pages
                        )
                        tier = getattr(eng.pool, "tier", None)
                        if tier is not None and tier.armed:
                            self._ledger.note_serve_tiers(
                                tier.pages_host,
                                tier.pages_disk,
                                eng.pool.tier_hits,
                            )
            if not did:
                time.sleep(self._idle_sleep_s)

    # ------------------------------------------------------ chaos verbs
    def kill(self) -> None:
        """Dead pod: stop stepping, fail held/new requests NOW, close
        both servers so the next fleet poll marks the row stale."""
        self._stop.set()
        self.gateway.aborted = True
        self.gateway.close()
        self.metrics.close()

    def stall(self) -> None:
        """Wedged device: sockets stay open, nothing ever finishes."""
        self._stalled.set()

    def unstall(self) -> None:
        self._stalled.clear()

    def drain(self) -> None:
        """SIGTERM: no new admissions (gateway 503s "draining", the
        ledger flips ``serve_draining`` so the fleet row carries it),
        in-flight work finishes, queued work re-routes."""
        self._draining = True
        self.gateway.draining = True
        self._ledger.note_serve_draining(True)

    def close(self) -> None:
        """Graceful teardown; safe after ``kill()`` (double-close of
        the servers is a no-op)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        for closer in (self.gateway.close, self.metrics.close):
            try:
                closer()
            except Exception:  # noqa: BLE001 — already-dead servers
                pass


def apply_replica_plan(
    replicas: dict[str, LocalReplica],
    plan: list[tuple[str, str, float]],
    *,
    t0: float | None = None,
) -> threading.Thread:
    """Execute a ``faults.replica_plan()`` schedule against live
    replicas on a timer thread: each ``(kind, id, at_s)`` fires
    ``kill()`` / ``stall()`` at ``t0 + at_s``. Unknown ids are skipped
    (the plan may name replicas another process owns). Returns the
    (daemon) thread; join it to know every fault has fired."""
    start = time.monotonic() if t0 is None else float(t0)

    def _run() -> None:
        for kind, target, at_s in sorted(plan, key=lambda x: x[2]):
            delay = start + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            rep = replicas.get(target)
            if rep is None:
                continue
            if kind in ("replica_kill", "prefill_kill"):
                # prefill_kill (ISSUE 19) is a replica_kill aimed at a
                # prefill-role replica: same dead-pod semantics, its
                # own spec name so one TPUFLOW_FAULT string can kill
                # the prefill worker mid-ship while decode replicas
                # ride out the loss via local-prefill fallback.
                rep.kill()
            elif kind == "replica_stall":
                rep.stall()

    th = threading.Thread(
        target=_run, name="tpuflow-chaos-plan", daemon=True
    )
    th.start()
    return th


# ------------------------------------------------------------- load side
def run_poisson(
    submit: Callable[[dict], dict],
    requests: list[dict],
    *,
    rate_qps: float,
    rng: np.random.Generator | None = None,
    jitter: bool = True,
) -> list[dict]:
    """Open-loop Poisson load: request k submits at the k-th arrival
    time regardless of how earlier requests are faring (that is what
    makes backpressure and failover observable). ``submit`` is either
    ``router.route`` or an HTTP POST through a FrontDoor.

    Returns one record per request — ``{"request", "response", "error",
    "outcome": "ok"|"rejected"|"error", "latency_s"}`` — in input
    order. ``rejected`` is an explicit FleetBusy 503; anything in
    ``error`` is a DROPPED request, which the chaos tests assert never
    happens."""
    rng = rng if rng is not None else np.random.default_rng(0)
    n = len(requests)
    if rate_qps <= 0:
        raise ValueError("rate_qps must be positive")
    gaps = (
        rng.exponential(1.0 / rate_qps, size=n)
        if jitter
        else np.full(n, 1.0 / rate_qps)
    )
    arrivals = np.cumsum(gaps)
    out: list[dict | None] = [None] * n
    t0 = time.monotonic()

    def _one(k: int, req: dict) -> None:
        delay = t0 + float(arrivals[k]) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        started = time.monotonic()
        rec: dict[str, Any] = {
            "request": req, "response": None, "error": None,
        }
        try:
            rec["response"] = submit(req)
            rec["outcome"] = "ok"
        except FleetBusy as e:
            rec["error"] = str(e)
            rec["outcome"] = "rejected"
        except Exception as e:  # noqa: BLE001 — accounted, asserted 0
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["outcome"] = "error"
        rec["latency_s"] = time.monotonic() - started
        out[k] = rec

    threads = [
        threading.Thread(
            target=_one, args=(k, r),
            name=f"tpuflow-load-{k}", daemon=True,
        )
        for k, r in enumerate(requests)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return [r for r in out if r is not None]
