"""Env-driven fault injection: every failure path exercisable in tests.

Activated by ``TPUFLOW_FAULT`` — a comma-separated list of fault specs
that the gang launcher's environment propagates into every member
process. Grammar (one spec per entry)::

    member_exit:<rank>@step<k>   die (os._exit(1)) on member <rank> at the
                                 end of train step/report <k>
    member_lost:<rank>@step<k>   like member_exit, but PERMANENT capacity
                                 loss: the elastic supervisor suppresses
                                 the member's relaunch, so the gang
                                 shrinks and stays shrunk (member_exit's
                                 relaunch exercises re-grow instead)
    rejoin_delay:<seconds>@<rank>
                                 a relaunched member <rank> sleeps before
                                 requesting to rejoin the elastic gang —
                                 a late rejoin whose grow fence races the
                                 survivors' step fences
    preempt:<rank>@step<k>       set the preemption flag on member <rank>
                                 at the end of step <k> (simulated SIGTERM:
                                 the loop drains a checkpoint and exits
                                 with the requeue code)
    nan_grad:<rank>@step<k>      poison member <rank>'s params before step
                                 <k> so that step's loss and gradients are
                                 NaN (single-shot — the replayed step after
                                 a health rollback runs clean); exercises
                                 the fused nonfinite detector end to end
    loss_spike:<rank>@step<k>    scale params ×1e3 before step <k>: a huge
                                 but finite loss/grad spike (single-shot)
                                 for the median+MAD spike detector
    heartbeat_stall:<rank>       member <rank> stops stamping heartbeats
                                 and hangs (simulated livelock) — the
                                 supervisor must detect and kill it
    rendezvous_delay:<seconds>[@<rank>]
                                 sleep before joining the jax.distributed
                                 rendezvous (all members, or just <rank>)
    ckpt_truncate                truncate the first raw shard file written
                                 after the spec activates (torn write)
    ckpt_flip_byte               flip one byte in the first raw shard file
                                 written after the spec activates (silent
                                 storage corruption — caught by the
                                 per-shard crc32 on restore)
    ckpt_io_flaky:p<n>           every distinct checkpoint I/O operation
                                 (op+path) raises a transient EIO on its
                                 first <n> attempts, succeeding after —
                                 proves the retry_io backoff layer; with
                                 <n> above the retry budget, proves the
                                 save-failed-cleanly path
    ckpt_partial_commit          single-shot: the next commit leaves its
                                 staged step-<k>.tmp dir on disk and never
                                 writes the marker (a writer killed
                                 between payload and commit) — the next
                                 manager's GC must reclaim it
    upload_stall[:<seconds>]     sleep in the local→persistent upload of
                                 the fast checkpoint tier (default 5 s) —
                                 a slow shared filesystem the async saver
                                 must absorb off the training path
    replica_kill:<id>@<t>        serving chaos (ISSUE 17): kill replica
                                 <id> (a fleet replica id, not a gang
                                 rank) <t> seconds into the chaos drive —
                                 its sockets close abruptly, like a pod
                                 death mid-decode; the router must
                                 re-dispatch its in-flight work
    replica_stall:<id>@<t>       serving chaos: replica <id> stops
                                 stepping <t> seconds in but its sockets
                                 stay open and hang — the livelock case
                                 only the router's per-replica forward
                                 timeout can detect
    prefill_kill:<id>@<t>        disaggregated serving chaos (ISSUE 19):
                                 kill the prefill-role replica <id> <t>
                                 seconds in — mid-ship death; the router
                                 must fall back to local prefill on the
                                 decode replicas without dropping or
                                 corrupting any request

Hooks are threaded through gang exec (``maybe_rendezvous_delay``), the
train loops (``step_boundary`` — called by ``TrainContext.report`` and
the GPT epoch loops), the heartbeat stamp (``maybe_stall_heartbeat``),
the raw saver (``corrupt_after_write``), the retrying I/O wrapper
(``ckpt_io_fault``) and the manager's commit/upload path
(``partial_commit`` / ``maybe_upload_stall``). Every hook is a no-op
costing one env lookup when ``TPUFLOW_FAULT`` is unset.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import os
import sys
import time
from tpuflow.utils import knobs


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    rank: int | None = None
    step: int | None = None
    value: float | None = None
    # Serving chaos (ISSUE 17): replicas are named by fleet id strings
    # (pod names), not integer gang ranks.
    target: str | None = None


KINDS = (
    "member_exit",
    "member_lost",
    "rejoin_delay",
    "preempt",
    "nan_grad",
    "loss_spike",
    "heartbeat_stall",
    "rendezvous_delay",
    "ckpt_truncate",
    "ckpt_flip_byte",
    "ckpt_io_flaky",
    "ckpt_partial_commit",
    "upload_stall",
    "replica_kill",
    "replica_stall",
    "prefill_kill",
)

# Parse cache keyed on the raw env string (tests flip the env between
# cases in one process); fired-once bookkeeping for the single-shot
# checkpoint corruptions; per-(op,path) injected-failure counts for the
# flaky-IO fault.
_CACHE: tuple[str, list[Fault]] | None = None
_FIRED: set[str] = set()
_IO_FLAKY_COUNTS: dict[str, int] = {}


def reset() -> None:
    """Forget fired single-shot faults (test isolation within a process)."""
    global _CACHE
    _CACHE = None
    _FIRED.clear()
    _IO_FLAKY_COUNTS.clear()


def parse(raw: str) -> list[Fault]:
    out = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        kind, _, payload = entry.partition(":")
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in TPUFLOW_FAULT={raw!r}; "
                f"known: {KINDS}"
            )
        rank = step = value = target = None
        if kind in (
            "member_exit", "member_lost", "preempt", "nan_grad", "loss_spike"
        ):
            rank_s, _, step_s = payload.partition("@")
            rank = int(rank_s)
            if not step_s.startswith("step"):
                raise ValueError(
                    f"{kind} spec needs '<rank>@step<k>', got {entry!r}"
                )
            step = int(step_s[len("step"):])
        elif kind == "heartbeat_stall":
            rank = int(payload)
        elif kind == "rendezvous_delay":
            secs_s, _, rank_s = payload.partition("@")
            value = float(secs_s)
            rank = int(rank_s) if rank_s else None
        elif kind == "rejoin_delay":
            secs_s, _, rank_s = payload.partition("@")
            if not rank_s:
                raise ValueError(
                    f"rejoin_delay spec needs '<seconds>@<rank>', got "
                    f"{entry!r}"
                )
            value = float(secs_s)
            rank = int(rank_s)
        elif kind == "ckpt_io_flaky":
            if not payload.startswith("p"):
                raise ValueError(
                    f"ckpt_io_flaky spec needs 'p<n>', got {entry!r}"
                )
            value = float(int(payload[1:]))
        elif kind == "upload_stall":
            value = float(payload) if payload else 5.0
        elif kind in ("replica_kill", "replica_stall", "prefill_kill"):
            target_s, _, t_s = payload.partition("@")
            if not target_s or not t_s:
                raise ValueError(
                    f"{kind} spec needs '<id>@<t>' (fleet replica id @ "
                    f"seconds into the drive), got {entry!r}"
                )
            target = target_s
            value = float(t_s)
        elif payload:
            raise ValueError(f"fault {kind} takes no payload, got {entry!r}")
        out.append(
            Fault(kind, rank=rank, step=step, value=value, target=target)
        )
    return out


def _specs() -> list[Fault]:
    global _CACHE
    raw = knobs.raw("TPUFLOW_FAULT", "")
    if not raw:
        return []
    if _CACHE is None or _CACHE[0] != raw:
        _CACHE = (raw, parse(raw))
    return _CACHE[1]


def matching(kind: str) -> list[Fault]:
    return [f for f in _specs() if f.kind == kind]


def active(kind: str) -> Fault | None:
    for f in _specs():
        if f.kind == kind:
            return f
    return None


def _rank() -> int:
    try:
        return int(knobs.raw("TPUFLOW_PROCESS_ID", "0"))
    except ValueError:
        return 0


# ------------------------------------------------------------------ hooks
def step_boundary(step: int) -> None:
    """Train-loop hook: called after step/report ``step`` committed."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return
    rank = _rank()
    for f in matching("preempt"):
        if f.rank == rank and f.step == step:
            from tpuflow.utils.preempt import request_preemption

            print(
                f"[faults] preempt injected at step {step}", file=sys.stderr
            )
            request_preemption()
    # member_lost (ISSUE 7) dies exactly like member_exit — the difference
    # lives in the ELASTIC SUPERVISOR, which reads the same spec from its
    # own env and suppresses the member's relaunch (permanent capacity
    # loss → the shrink sticks; member_exit's relaunch exercises re-grow).
    for kind in ("member_exit", "member_lost"):
        for f in matching(kind):
            if f.rank == rank and f.step == step:
                print(
                    f"[faults] {kind} injected at step {step}",
                    file=sys.stderr,
                )
                sys.stderr.flush()
                # os._exit skips atexit, so the recorder's close() never
                # runs: drain the telemetry buffer here or the dying
                # member's last steps vanish from events.jsonl (ISSUE 3
                # satellite). The flight dump first (ISSUE 6): an injected
                # member death is exactly the fatal path whose forensic
                # artifact the supervisor's flow.member_failed /
                # flow.member_lost event references.
                from tpuflow import obs
                from tpuflow.obs import flight

                flight.dump_flight(f"faults.{kind}")
                obs.flush()
                os._exit(1)


_POISON = {"nan_grad": float("nan"), "loss_spike": 1e3}


def grad_poison(step: int) -> float | None:
    """Train-loop hook: a parameter multiplier to apply BEFORE executing
    optimizer step ``step``, or None. ``nan_grad`` poisons params with NaN
    (→ NaN loss and gradients inside the jitted step, tripping the fused
    nonfinite flag); ``loss_spike`` scales params ×1e3 (finite spike for
    the median+MAD detector). Single-shot per spec: after a health
    rollback the replayed step runs clean, so detection → rollback →
    recovery is provable end to end."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return None
    rank = _rank()
    for kind, mult in _POISON.items():
        for f in matching(kind):
            if f.rank != rank or f.step != step:
                continue
            key = f"{kind}:{f.rank}@{f.step}"
            if key in _FIRED:
                continue
            _FIRED.add(key)
            print(
                f"[faults] {kind} injected before step {step}",
                file=sys.stderr,
            )
            return mult
    return None


def maybe_rendezvous_delay() -> None:
    """Gang-bootstrap hook: called before ``jax.distributed`` rendezvous."""
    for f in matching("rendezvous_delay"):
        if f.rank is None or f.rank == _rank():
            time.sleep(f.value or 0.0)


def maybe_rejoin_delay() -> None:
    """Elastic-rejoin hook (gang_exec): a relaunched member sleeps before
    requesting inclusion in the next grow generation — the late-rejoin
    case where the grow fence races the survivors' step fences."""
    for f in matching("rejoin_delay"):
        if f.rank == _rank():
            print(
                f"[faults] rejoin_delay: sleeping {f.value}s before the "
                "join request",
                file=sys.stderr,
            )
            sys.stderr.flush()
            time.sleep(f.value or 0.0)


def maybe_stall_heartbeat() -> None:
    """Heartbeat hook: a matching member hangs here (never stamps again),
    simulating a livelocked process the supervisor must reap."""
    for f in matching("heartbeat_stall"):
        if f.rank == _rank():
            print("[faults] heartbeat_stall: hanging", file=sys.stderr)
            sys.stderr.flush()
            time.sleep(3600.0)


def ckpt_io_fault(op: str, path: str) -> None:
    """retry_io hook: with ``ckpt_io_flaky:p<n>`` active, every distinct
    (op, path) pair raises a *transient* EIO on its first <n> attempts and
    succeeds afterwards — deterministic, so tests can pin both "retries
    absorb the blip" (<n> ≤ retry budget) and "the save fails cleanly"
    (<n> > budget)."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return
    f = active("ckpt_io_flaky")
    if f is None:
        return
    n = int(f.value or 0)
    key = f"{op}:{path}"
    fired = _IO_FLAKY_COUNTS.get(key, 0)
    if fired < n:
        _IO_FLAKY_COUNTS[key] = fired + 1
        raise OSError(
            _errno.EIO,
            f"[faults] injected flaky IO ({fired + 1}/{n}) for {op}",
            path,
        )


def partial_commit() -> bool:
    """Commit hook: with ``ckpt_partial_commit`` active, return True ONCE
    — the manager then leaves the staged ``.tmp`` dir in place without a
    commit marker, emulating a writer killed between payload and commit."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return False
    if active("ckpt_partial_commit") is None or "ckpt_partial_commit" in _FIRED:
        return False
    _FIRED.add("ckpt_partial_commit")
    print("[faults] ckpt_partial_commit: leaving staged dir", file=sys.stderr)
    return True


def maybe_upload_stall() -> None:
    """Upload hook: with ``upload_stall[:s]`` active, sleep inside the
    local→persistent copy — a slow shared filesystem the async saver must
    absorb without stalling training."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return
    f = active("upload_stall")
    if f is not None:
        print(
            f"[faults] upload_stall: sleeping {f.value}s", file=sys.stderr
        )
        time.sleep(f.value or 0.0)


def replica_plan() -> list[tuple[str, str, float]]:
    """Serving-chaos hook (ISSUE 17): the ``(kind, replica_id, at_s)``
    schedule of every ``replica_kill``/``replica_stall`` spec, sorted by
    fire time. The chaos harness (``tpuflow.testing.chaos``) applies it
    against its in-process replicas; the ``serving.router`` bench leg
    reads the same specs, so one ``TPUFLOW_FAULT`` string drives both.
    One env lookup when unset."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return []
    plan = [
        (f.kind, f.target or "", float(f.value or 0.0))
        for f in _specs()
        if f.kind in (
            "replica_kill", "replica_stall", "prefill_kill"
        )
    ]
    plan.sort(key=lambda x: x[2])
    return plan


def corrupt_after_write(path: str) -> None:
    """Raw-saver hook: single-shot corruption of the first shard written
    after the spec activates (crc32 in the manifest was computed from the
    in-memory bytes, so restore-side verification must catch this)."""
    if not knobs.raw("TPUFLOW_FAULT"):
        return
    for kind in ("ckpt_truncate", "ckpt_flip_byte"):
        if active(kind) is None or kind in _FIRED:
            continue
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        if size == 0:
            continue
        _FIRED.add(kind)
        if kind == "ckpt_truncate":
            os.truncate(path, size // 2)
        else:
            with open(path, "r+b") as f:
                f.seek(size // 2)
                byte = f.read(1)
                f.seek(size // 2)
                f.write(bytes([byte[0] ^ 0xFF]))
        print(f"[faults] {kind} applied to {path}", file=sys.stderr)
