"""Distributed training runtime (Trainer, configs, context, Result)."""

from tpuflow.train.step import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_eval_step",
    "make_train_step",
]
