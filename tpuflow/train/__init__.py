"""Distributed training runtime (Trainer, configs, context, Result)."""

# Re-exported for callers catching the health observatory's halt (its
# home is tpuflow.obs.health, next to the detectors that raise it).
from tpuflow.obs.health import TrainingDiverged
from tpuflow.train.gpt import GptTrainConfig, GptTrainResult, train_gpt
from tpuflow.train.optim import make_optimizer, make_schedule
from tpuflow.train.step import (
    DispatchWindow,
    TrainState,
    create_train_state,
    dispatch_depth,
    make_eval_step,
    make_train_step,
    per_worker_batch_size,
    run_validation,
    with_ema,
)
from tpuflow.train.trainer import (
    CheckpointConfig,
    Result,
    RunConfig,
    ScalingConfig,
    TrainContext,
    Trainer,
    get_context,
)

__all__ = [
    "CheckpointConfig",
    "DispatchWindow",
    "GptTrainConfig",
    "GptTrainResult",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "TrainState",
    "Trainer",
    "TrainingDiverged",
    "create_train_state",
    "dispatch_depth",
    "get_context",
    "make_eval_step",
    "make_optimizer",
    "make_schedule",
    "make_train_step",
    "per_worker_batch_size",
    "run_validation",
    "with_ema",
]
