"""GPT-family training recipes: FSDP (+ tensor/sequence/expert parallel)
and GPipe pipeline legs, as library functions.

The flows stay reference-sized shells (reference train_flow.py is a
~100-line wrapper over its library stack; its counterpart here just binds
CLI parameters to ``GptTrainConfig`` and records artifacts) — everything
a new model family or dataset would want to reuse lives in this module:
mesh/sharding setup, resume, the epoch loop with held-out validation,
checkpointing with retention/best, EMA, and post-train sampling.

Covers BASELINE.md config 5 ("GPT-2-medium FSDP → pjit fully-sharded
checkpoint, multi-host v5e-32") with the framework's idioms: parameters
and optimizer state born sharded over ('fsdp','data') (optionally
tensor-parallel over 'tensor', sequence-parallel over 'seq',
expert-parallel over 'expert'), per-epoch async sharded checkpoints, and
full-state resume.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import time
from typing import Any

from tpuflow.utils import knobs
from tpuflow.utils.preempt import (
    Preempted,
    emergency_save_advised,
    launch_attempt,
    preemption_requested,
)


def _resume_cursor(
    data_state: dict | None,
    step: int,
    steps_per_epoch: int,
    epochs: int,
    seed: int,
) -> tuple[int, int]:
    """(start_epoch, batches_to_skip) for a resume/rollback landing on
    ``step``.

    With a persisted loader cursor (ISSUE 5: checkpoint metadata
    ``data_state``) whose shuffle seed matches, the resume lands
    mid-epoch and replays exactly the epoch's unconsumed tail — no batch
    trained twice, none dropped. Without one (pre-cursor checkpoints, or
    a reseeded loader whose permutation no longer matches) fall back to
    the epoch head the step floors to, as before."""
    if data_state and int(data_state.get("seed", -1)) == int(seed):
        epoch = min(int(data_state.get("epoch", 0)), epochs)
        skip = max(int(data_state.get("batch_index", 0)), 0)
        if skip >= steps_per_epoch:
            # Drained exactly at the epoch boundary: next epoch, no skip.
            return min(epoch + 1, epochs), 0
        return epoch, skip
    return min(step // steps_per_epoch, epochs), 0


def _apply_remat_selector(model_cfg, selector: str):
    """Map a remat selector onto a GPT2Config (ISSUE 10).

    ``none``  — remat OFF: every activation saved, including the flash
                custom_vjp residuals (outputs + lse) — the backward runs
                ZERO recompute. The fastest step when HBM admits it.
    ``dots``  — remat ON with the 'dots' policy: MXU dot outputs and the
                named flash output saved, cheap elementwise recomputed
                (the TPU-standard middle ground).
    ``full``  — remat ON, nothing saved beyond block inputs: minimum
                memory, maximum recompute (the old full-size default).
    Any other value: treated as a literal jax.checkpoint_policies name
    (validated by the caller), remat ON.
    """
    if selector == "none":
        return dataclasses.replace(
            model_cfg, remat=False, remat_policy=None
        )
    if selector == "full":
        return dataclasses.replace(
            model_cfg, remat=True, remat_policy=None
        )
    # 'dots' or an explicit checkpoint_policies name: a policy only
    # means anything under remat — asking for one turns remat on
    # (otherwise the knob is silently inert on presets that default
    # remat off, like 'test').
    return dataclasses.replace(
        model_cfg, remat=True, remat_policy=selector
    )


def active_remat_policy(model_cfg) -> str:
    """The resolved selector string for telemetry: 'none' when remat is
    off, 'full' for policy-less remat, else the policy name."""
    if not model_cfg.remat:
        return "none"
    return model_cfg.remat_policy or "full"


@dataclasses.dataclass
class GptTrainConfig:
    """Everything the GPT training recipes need; flows bind CLI parameters
    straight onto this (defaults match the flow defaults)."""

    preset: str = "test"            # test | gpt2 | medium
    epochs: int = 2
    steps_per_epoch: int = 16
    batch_size: int = 8             # global
    seq_len: int = 64
    learning_rate: float = 3e-4
    data_axis: int = 2
    fsdp_axis: int = 2
    tensor_axis: int = 1
    seq_axis: int = 1
    expert_axis: int = 1
    experts: int = 0                # Switch-MoE experts per block (0=dense)
    stage_axis: int = 1             # >1 = GPipe pipeline mode
    microbatches: int = 2
    attn_impl: str = "auto"         # auto | xla | flash | ring | ulysses
    dataset: str = "lm_synth"       # lm_synth | lm_text
    text_path: str | None = None    # pin the lm_text corpus file
    sample_tokens: int = 0
    accum_steps: int = 1
    optimizer_name: str = "adamw"   # adamw | sgd | adafactor | lion
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    grad_clip: float = 0.0
    weight_decay: float = 1e-4
    ema_decay: float = 0.0
    ckpt_dtype: str | None = None
    decay_steps: int = 0            # 0 = this run's epochs*steps
    # Selective-remat policy for the full-size presets (which remat by
    # default): '' = full remat, else a jax.checkpoint_policies name,
    # e.g. 'dots_with_no_batch_dims_saveable' (save MXU outputs,
    # recompute the cheap elementwise bulk).
    remat_policy: str = ""
    # Activation dtype: '' = f32, 'bfloat16' = the standard TPU
    # mixed-precision recipe (bf16 MXU operands, f32 master weights +
    # optimizer state + loss head — checkpoints are unchanged).
    dtype: str = ""

    def model_config(self):
        import jax.numpy as jnp

        from tpuflow.models.gpt2 import GPT2Config

        act_dtype = None
        if self.dtype:
            if self.dtype not in ("bfloat16", "float16", "float32"):
                raise ValueError(
                    f"unknown dtype {self.dtype!r}; supported: bfloat16, "
                    "float16, float32"
                )
            act_dtype = jnp.dtype(self.dtype)
        cfg = GPT2Config.from_preset(
            self.preset,
            attn_impl=self.attn_impl,
            seq_len=self.seq_len,
            stage_axis=self.stage_axis,
            n_experts=self.experts,
            dtype=act_dtype,
        )
        if self.remat_policy:
            import jax

            if self.remat_policy not in (
                "full", "dots", "none"
            ) and not hasattr(jax.checkpoint_policies, self.remat_policy):
                # Fail at config time, not at first jit trace inside an
                # already-provisioned training job.
                raise ValueError(
                    f"unknown remat_policy {self.remat_policy!r}; valid "
                    "names are full|dots|none or the "
                    "jax.checkpoint_policies attributes "
                    "(e.g. dots_with_no_batch_dims_saveable)"
                )
            cfg = _apply_remat_selector(cfg, self.remat_policy)
        # The env selector (ISSUE 10) beats the config: a provisioned
        # run flips its memory/recompute trade per launch without a
        # config edit — the MFU-push knob for remat-off training, where
        # the flash custom_vjp residuals (outputs + lse) are SAVED from
        # the forward instead of re-running every block's kernels.
        env_sel = knobs.raw("TPUFLOW_REMAT_POLICY", "").strip()
        if env_sel:
            if env_sel not in ("full", "dots", "none"):
                # Config-time failure, same contract as a bad
                # remat_policy — never a mid-provisioning trace crash.
                raise ValueError(
                    f"TPUFLOW_REMAT_POLICY={env_sel!r}; valid selectors "
                    "are full|dots|none"
                )
            cfg = _apply_remat_selector(cfg, env_sel)
        return cfg

    def optimizer(self):
        from tpuflow.train.optim import make_optimizer

        total = self.epochs * self.steps_per_epoch
        return make_optimizer(
            self.learning_rate,
            optimizer=self.optimizer_name,
            weight_decay=self.weight_decay,
            grad_clip_norm=self.grad_clip or None,
            warmup_steps=self.warmup_steps,
            decay_steps=self.decay_steps
            or max(total - self.warmup_steps, 1),
            schedule=self.lr_schedule,
        )

    def validate(self) -> None:
        """Reject incoherent knob combinations with actionable messages."""
        if self.stage_axis > 1:
            # Pipeline composes with data parallelism only.
            if (
                self.tensor_axis > 1
                or self.seq_axis > 1
                or self.expert_axis > 1
            ):
                raise ValueError(
                    "pipeline (stage_axis) composes with data_axis only"
                )
            if self.accum_steps > 1:
                raise ValueError(
                    "accum_steps applies to the FSDP/DP step only; the "
                    "pipeline schedule already microbatches via "
                    "microbatches"
                )
            if self.ema_decay > 0.0:
                raise ValueError(
                    "ema_decay is not supported in pipeline mode "
                    "(stage_axis > 1); the pipeline step tracks no EMA"
                )
        if self.experts and self.experts % self.expert_axis:
            raise ValueError(
                f"experts {self.experts} must be divisible by "
                f"expert_axis {self.expert_axis}"
            )


@dataclasses.dataclass
class GptTrainResult:
    checkpoint: Any                  # CheckpointHandle of the final save
    loss_history: list[float]
    metrics_history: list[dict]
    sample: list[int] | None = None  # greedy tokens when sample_tokens > 0


def _loaders(cfg: GptTrainConfig, vocab: int):
    from tpuflow.data.lm import make_lm_loaders

    return make_lm_loaders(
        cfg.batch_size, cfg.steps_per_epoch, cfg.seq_len, vocab,
        dataset=cfg.dataset, text_path=cfg.text_path,
    )


def train_gpt(
    cfg: GptTrainConfig, ckpt_dir: str, resume_checkpoint=None,
    log=print,
) -> GptTrainResult:
    """Run the configured GPT training leg end to end.

    ``resume_checkpoint``: a CheckpointHandle to restore FULL state from
    (step, params, opt_state, and — when ``ema_decay`` matches — the
    averaged weights). Callers that know the handle early should
    ``tpuflow.ckpt.prewarm_restore_handle`` it before calling, so the
    restore's page backing overlaps the setup work here.
    """
    cfg.validate()
    # Startup latency: point XLA's persistent compilation cache at a
    # durable directory (default $TPUFLOW_HOME/compile_cache; set
    # TPUFLOW_COMPILE_CACHE=run to key it under this run's directory —
    # the right mode when only the run dir is on shared storage, e.g. a
    # requeued k8s gang whose pod-local home is ephemeral). Retried /
    # requeued / resumed attempts then load the compiled step instead of
    # re-paying the 20-40 s TPU compile (BENCH_r05: compile_s 62.9,
    # wall_to_first_step 125.1 s).
    from tpuflow import dist as _dist

    _dist.maybe_enable_compile_cache(
        run_dir=os.path.dirname(os.path.abspath(ckpt_dir))
    )
    # Async-collective scheduling flags for the comm-overlap path
    # (ISSUE 10) — a best-effort staging for in-process runs: only
    # effective when no jax backend is up yet (gang members stage them
    # in gang_exec before ANY backend touch; libtpu reads the env once).
    _dist.maybe_enable_async_collectives()
    # Live metrics endpoint (ISSUE 6, opt-in TPUFLOW_OBS_HTTP_PORT): gang
    # member 0 — or an in-process run, which is its own member 0 — serves
    # /metrics + /status for the duration of the leg. Idempotent; one
    # env lookup when the knob is off.
    from tpuflow.obs import export as _obs_export

    _obs_export.maybe_start_from_env()
    if cfg.stage_axis > 1:
        if cfg.fsdp_axis > 1:
            log(
                "[gpt] note: fsdp_axis does not apply in pipeline mode; "
                "params shard by layer slice over 'stage' instead"
            )
        return _train_pipeline(cfg, ckpt_dir, resume_checkpoint, log)
    return _train_fsdp(cfg, ckpt_dir, resume_checkpoint, log)


def _train_fsdp(
    cfg: GptTrainConfig, ckpt_dir: str, resume_checkpoint, log
) -> GptTrainResult:
    """FSDP leg, elastic-aware (ISSUE 7): each pass of this loop is one
    mesh GENERATION. A ``MeshReform`` unwinding the generation body (a
    pending plan seen at a step fence, or a collective that died with a
    member) has already handed state to the checkpoint; re-rendezvous and
    re-enter — the in-run resume machinery restores the state (resharded,
    bit-identical), the histories, and the mid-epoch data cursor exactly
    as it would for a requeued attempt, minus the process restart."""
    from tpuflow.dist import membership as _membership

    while True:
        try:
            return _run_fsdp_generation(
                cfg, ckpt_dir, resume_checkpoint, log
            )
        except _membership.MeshReform as rf:
            log(
                f"[gpt] mesh re-form → generation {rf.plan.generation} "
                f"({rf.plan.reason}, {rf.plan.num_processes} members)"
            )
            _membership.quiesce_and_reform(rf.plan)
            # The next generation resumes from the manager's newest
            # committed step, never the original cross-run handle.
            resume_checkpoint = None


def _run_fsdp_generation(
    cfg: GptTrainConfig, ckpt_dir: str, resume_checkpoint, log
) -> GptTrainResult:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuflow import dist
    from tpuflow.ckpt import CheckpointManager
    from tpuflow.dist import membership as _membership
    from tpuflow.models.gpt2 import GPT2
    from tpuflow.parallel import create_sharded_state, gpt2_tensor_rules
    from tpuflow.train import (
        TrainState,
        make_eval_step,
        make_train_step,
        run_validation,
    )

    model_cfg = cfg.model_config()
    axes = {
        "data": cfg.data_axis,
        "fsdp": cfg.fsdp_axis,
        "tensor": cfg.tensor_axis,
        "seq": cfg.seq_axis,
        "expert": cfg.expert_axis,
    }
    generation = (
        _membership.current_generation() if _membership.enabled() else 0
    )
    if generation > 0:
        # Post-reform world: the data axis absorbs the resize (the
        # model-parallel axes are fixed by the architecture). A world the
        # fixed axes don't divide keeps the configured shape and fails
        # loudly in make_mesh — the supervisor's requeue floor should
        # have prevented it.
        ndev = len(jax.devices())
        fixed = (
            axes["fsdp"] * axes["tensor"] * axes["seq"] * axes["expert"]
        )
        if fixed > 0 and ndev % fixed == 0:
            axes["data"] = ndev // fixed
    mesh = dist.make_mesh(axes)
    log(
        f"[gpt] mesh {dict(mesh.shape)}, preset {cfg.preset}"
        + (f", generation {generation}" if generation else "")
    )
    model = GPT2(model_cfg)
    tx = cfg.optimizer()

    def init_fn(rng):
        params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    with mesh:
        mgr = CheckpointManager(
            ckpt_dir, max_to_keep=2, save_dtype=cfg.ckpt_dtype or None
        )
        # In-run resume (retry / preemption requeue): a previous attempt of
        # THIS run left committed checkpoints in ckpt_dir — continue from
        # the newest instead of restarting at step 0 (the manager already
        # rebuilt the full metrics history from it at construction). An
        # explicit resume_checkpoint handle (cross-run --from-run) wins.
        resume_step = (
            mgr.latest_step() if resume_checkpoint is None else None
        )
        t_phase = time.monotonic()
        state, shardings = create_sharded_state(
            init_fn,
            mesh,
            jax.random.PRNGKey(0),
            fsdp=True,
            # The rules carry BOTH tensor and expert placements and
            # self-gate on axis sizes.
            tensor_rules=gpt2_tensor_rules
            if cfg.tensor_axis > 1 or cfg.expert_axis > 1
            else None,
            # On resume the state is built ABSTRACTLY (shape eval only):
            # materializing 355M random params + zeroed moments just to
            # overwrite every leaf with the restore doubled resume wall
            # time (MEDIUM_RUNS.md r3: fresh 103 s vs resume 206 s).
            materialize=resume_checkpoint is None and resume_step is None,
        )
        resuming = resume_checkpoint is not None or resume_step is not None
        log(f"[gpt] state {'template' if resuming else 'init'}:"
            f" {time.monotonic() - t_phase:.1f}s")
        if resuming:
            # state IS the abstract template here (materialize=False
            # returns sharding-annotated ShapeDtypeStructs).
            tmpl = {
                "step": state.step,
                "params": state.params,
                "opt_state": state.opt_state,
            }
            if cfg.ema_decay > 0.0:
                # EMA runs save/restore the averaged weights too; the
                # resume run must pass the same ema_decay (the checkpoint's
                # leaf structure includes them).
                tmpl["ema_params"] = state.params
            t_phase = time.monotonic()
            if resume_checkpoint is not None:
                from tpuflow.ckpt import restore_from_handle

                restored = restore_from_handle(
                    resume_checkpoint, abstract_state=tmpl
                )
            else:
                # crc-verified; falls back to the previous committed step
                # (with a ckpt.corrupt event) if the newest is damaged.
                restored = mgr.restore(resume_step, abstract_state=tmpl)
            jax.block_until_ready(restored)
            # Direct construction — no init ran, there is no state to
            # .replace() over. batch_stats: GPT has none.
            state = TrainState(
                step=restored["step"],
                apply_fn=model.apply,
                params=restored["params"],
                tx=tx,
                opt_state=restored["opt_state"],
                batch_stats={},
                # Present exactly when the template asked for it (the raw
                # restore errors on any structure mismatch).
                ema_params=restored.get("ema_params", {}),
            )
            log(f"[gpt] full sharded state restored"
                f"{' (in-run resume)' if resume_step is not None else ''}:"
                f" {time.monotonic() - t_phase:.1f}s")

        loader, val_loader = _loaders(cfg, model_cfg.vocab_size)
        seq_spec = "seq" if cfg.seq_axis > 1 else None
        batch_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("data", "fsdp"), seq_spec)
        )
        if cfg.ema_decay > 0.0 and not state.ema_params:
            # Seed EMA only on fresh starts — a resume above already
            # restored the averaged weights.
            from tpuflow.train import with_ema

            state = with_ema(state)
        # Comm/compute overlap (ISSUE 10): hand the accumulation scan
        # the param shardings so each microbatch's gradients reduce-
        # scatter inside the scan body (hidden behind the next
        # microbatch's backward) instead of one exposed reduction after
        # it. Loss-bit-identical to the sequential scan (pinned by
        # tests/test_train_step.py); TPUFLOW_COMM_OVERLAP=0 recovers the
        # old program.
        from tpuflow.train.step import comm_overlap_enabled

        overlap = comm_overlap_enabled() and cfg.accum_steps > 1
        train_step = make_train_step(
            accum_steps=cfg.accum_steps,
            ema_decay=cfg.ema_decay or None,
            grad_shardings=shardings.params if overlap else None,
            comm_overlap=overlap,
        )
        eval_step = make_eval_step()
        rng = jax.random.PRNGKey(1)
        history = []
        epoch_records = []
        # In-run resume: seed the returned histories from the manager's
        # rebuilt metrics history, so the result is continuous across the
        # retry (no gap, no step-0 restart). Drain-only checkpoints (a
        # preemption's final save carries no metrics) are skipped.
        if resume_step is not None:
            for m in mgr._metrics_history:
                if "train_loss" not in m:
                    continue
                history.append(m["train_loss"])
                epoch_records.append(
                    {
                        "epoch": len(epoch_records),
                        "train_loss": m.get("train_loss"),
                        "val_loss": m.get("val_loss"),
                        "ppl": m.get("ppl"),
                        "tokens_per_s": None,
                    }
                )
        start_epoch = 0
        resume_skip = 0
        if resume_step is not None:
            start_epoch, resume_skip = _resume_cursor(
                (mgr._read_meta(resume_step) or {}).get("data_state"),
                int(state.step), cfg.steps_per_epoch, cfg.epochs,
                loader.seed,
            )
            log(
                f"[gpt] in-run resume from step {int(state.step)} "
                f"→ epoch {start_epoch}"
                + (
                    f" (replaying from batch {resume_skip})"
                    if resume_skip
                    else ""
                )
            )
        opt_step = int(state.step)
        # Telemetry (tpuflow.obs): per-step wall times + tokens ride the
        # fences the loop already pays; batch-wait rides the prefetch
        # iterator. All no-ops when obs is disabled.
        from tpuflow import obs
        from tpuflow.data.loader import prefetch_to_device
        from tpuflow.obs import goodput as goodput_mod
        from tpuflow.obs import health as health_mod
        from tpuflow.train.step import (
            DispatchWindow,
            StepClock,
            dispatch_depth,
        )

        # Training-health observatory (ISSUE 3): the monitor judges each
        # fenced step's numerics (None when TPUFLOW_HEALTH=0 — one
        # ``is not None`` check per step), the profile window wraps the
        # TPUFLOW_PROFILE step range in a jax.profiler trace.
        monitor = health_mod.HealthMonitor.from_env()
        profile = health_mod.ProfileWindow.from_env()
        lr_scale = 1.0
        fault_env = bool(knobs.raw("TPUFLOW_FAULT"))
        elastic = _membership.enabled()

        # Dispatch-ahead (ISSUE 4): up to `depth` steps run in flight;
        # the oldest step's scalars are settled (the float() host copies
        # below, which ARE the fence) only when the window fills, at
        # epoch end, and at every preemption/profile drain point — so
        # health rollback and requeue still land on a committed step
        # boundary, just observed up to depth-1 steps late.
        window = DispatchWindow(dispatch_depth())
        obs.gauge("train.dispatch_depth", float(window.depth))
        # Remat-selector + overlap provenance (ISSUE 10): one event per
        # leg so a run's memory/recompute trade and comm scheduling are
        # auditable from the stream alone.
        obs.event(
            "train.remat_policy",
            policy=active_remat_policy(model_cfg),
            comm_overlap=bool(overlap),
            accum_steps=cfg.accum_steps,
        )
        # FSDP world for the comm roofline: the axes grads actually
        # reduce over (same rule as parallel.make_shardings).
        fsdp_world = 1
        for _ax in ("fsdp", "data"):
            if mesh.shape.get(_ax, 1) > 1:
                fsdp_world *= int(mesh.shape[_ax])

        def settle(entry) -> None:
            """Fence one matured step and run its host-side accounting
            (telemetry, health monitor). Raises _RollbackSignal when the
            monitor flags the step. ``timed`` is False for the cold
            (compile) step, which records train.compile instead of a
            train.step_s observation."""
            step_no, metrics, tokens, timed = entry
            if monitor is not None or clock.recording:
                # 4-byte host copies; the first one blocks until the
                # step's program finished (the fence).
                nf = bool(float(metrics["nonfinite"]))
                m_loss = float(metrics["loss"])
                m_gn = float(metrics["grad_norm"])
                if clock.recording:
                    if timed:
                        clock.step_done(tokens=tokens, step=step_no)
                    clock.health_done(
                        loss=m_loss,
                        grad_norm=m_gn,
                        update_norm=float(metrics["update_norm"]),
                        param_norm=float(metrics["param_norm"]),
                        nonfinite=nf,
                    )
                if monitor is not None:
                    anomaly = monitor.observe(
                        step_no, m_loss, m_gn, nonfinite=nf
                    )
                    if anomaly is not None:
                        target = health_mod.handle_anomaly(
                            monitor, anomaly, mgr
                        )
                        raise health_mod._RollbackSignal(target, anomaly)
            else:
                # No consumer for the scalars: still fence, so the
                # window bounds the in-flight dispatch queue.
                jax.block_until_ready(metrics["loss"])
                if timed:
                    clock.step_done(tokens=tokens, step=step_no)

        def drain_window() -> None:
            for entry in window.drain():
                settle(entry)

        def drain_preempt() -> None:
            # SIGTERM landed (or was injected): commit a final checkpoint
            # at the current step and hand back Preempted — gang_exec
            # converts it into the requeue exit code, and the supervisor
            # reruns the step without consuming the retry budget. The
            # window drains first so the saved step is a settled one.
            drain_window()
            payload = {
                "step": state.step,
                "params": state.params,
                "opt_state": state.opt_state,
            }
            if cfg.ema_decay > 0.0:
                payload["ema_params"] = state.ema_params
            data_state = loader.state_dict(cursor["batch"])
            if mgr.latest_step() != opt_step:
                if emergency_save_advised():
                    # Closing grace window (ISSUE 5): synchronous commit
                    # on the fastest tier, upload skipped — the requeued
                    # attempt resumes from THIS step, not the last
                    # periodic save.
                    mgr.emergency_save(
                        opt_step, payload, data_state=data_state
                    )
                else:
                    mgr.save(
                        opt_step, payload, metrics={},
                        data_state=data_state,
                    )
                    mgr.wait_until_finished()
            mgr.close()
            raise Preempted(f"drained checkpoint at step {opt_step}")

        def drain_reform(plan) -> None:
            # Mesh re-form fence (ISSUE 7): hand state to the checkpoint
            # and unwind to the generation loop. At a grow fence every
            # member is alive, so the CURRENT step commits (the
            # emergency-checkpoint-if-none-fresh clause); after a loss
            # the survivors cannot assemble a full sharded checkpoint —
            # the stranded save is abandoned and resume replays from the
            # last FULLY committed step.
            if plan.reason == "grow":
                drain_window()
                payload = {
                    "step": state.step,
                    "params": state.params,
                    "opt_state": state.opt_state,
                }
                if cfg.ema_decay > 0.0:
                    payload["ema_params"] = state.ema_params
                if mgr.latest_step() != opt_step:
                    mgr.save(
                        opt_step, payload, metrics={},
                        data_state=loader.state_dict(cursor["batch"]),
                    )
                mgr.wait_until_finished()
            else:
                window.clear()
                mgr.abandon_pending()
            mgr.close()
            raise _membership.MeshReform(plan)

        def place_batch(b):
            # Runs on the prefetch thread: host→device placement onto
            # the step's exact batch sharding overlaps device compute.
            return {
                "x": jax.device_put(b["x"], batch_sharding),
                "y": jax.device_put(b["y"], batch_sharding),
            }

        clock = StepClock()
        # Rolling-MFU feed for the live export endpoint: the dense-
        # transformer 6·N FLOP/token estimate (set AFTER the clock reset
        # the ledger). state.params is materialized by now on both the
        # fresh and the restored path.
        n_params = sum(
            int(l.size) for l in jax.tree_util.tree_leaves(state.params)
        )
        goodput_mod.live().set_model_flops_per_token(6.0 * n_params)
        cold = True
        # Loader cursor for deterministic mid-epoch resume: epoch + batches
        # consumed, persisted as checkpoint data_state and replayed by
        # skip_batches on the restoring side.
        pending_skip = resume_skip
        cursor = {"batch": 0}
        while True:
            try:
                for epoch in range(start_epoch, cfg.epochs):
                    t_epoch = time.monotonic()
                    ts_epoch = time.time()
                    loader.set_epoch(epoch)
                    cursor["batch"] = pending_skip
                    if pending_skip:
                        loader.skip_batches(pending_skip)
                        pending_skip = 0
                    losses = []
                    n_tokens = 0
                    clock.reset()
                    for batch in prefetch_to_device(
                        loader, mesh, keys=("x", "y"), place=place_batch
                    ):
                        if fault_env:
                            from tpuflow.testing import faults

                            poison = faults.grad_poison(opt_step + 1)
                            if poison is not None:
                                state = state.replace(
                                    params=jax.tree_util.tree_map(
                                        lambda p: p * poison, state.params
                                    )
                                )
                        if profile is not None:
                            profile.maybe_start(opt_step + 1)
                        state, metrics = train_step(state, batch, rng)
                        losses.append(metrics["loss"])
                        tokens = int(np.prod(batch["y"].shape))
                        if cold:
                            # Fence out jit compilation so throughput
                            # numbers are comparable across epochs; the
                            # first batch's tokens are excluded from the
                            # rate accordingly. The cold step settles
                            # inline (never enters the window).
                            jax.block_until_ready(metrics["loss"])
                            t_epoch = time.monotonic()
                            ts_epoch = time.time()
                            compile_s = clock.compile_done(
                                preset=cfg.preset
                            )
                            if compile_s is not None:
                                # Device ledger compile-fence entry
                                # (ISSUE 15): re-lowering is trace-only
                                # (no XLA compile) — cost analysis +
                                # compile wall for the step program.
                                from tpuflow.obs import device as _devmod

                                _devmod.note_jit_program(
                                    "train.step",
                                    train_step,
                                    (state, batch, rng),
                                    compile_s=compile_s,
                                )
                            cold = False
                            opt_step += 1
                            settle((opt_step, metrics, 0, False))
                        else:
                            # No-op on accelerators; blocking on the
                            # serialized host-CPU platform (at most one
                            # collective program in flight there — see
                            # dist.serialize_steps).
                            dist.step_fence(metrics["loss"])
                            n_tokens += tokens
                            opt_step += 1
                            for entry in window.push(
                                (opt_step, metrics, tokens, True)
                            ):
                                settle(entry)
                        cursor["batch"] += 1
                        if profile is not None:
                            # Keep execution inside the trace window:
                            # effectively dispatch depth 1 while the
                            # profiler is live (rare, bounded by the
                            # TPUFLOW_PROFILE step range).
                            drain_window()
                            profile.maybe_stop(opt_step)
                        if fault_env:
                            from tpuflow.testing import faults

                            faults.step_boundary(opt_step)
                        if preemption_requested():
                            drain_preempt()
                        if elastic:
                            plan = _membership.pending_reform()
                            if plan is not None:
                                drain_reform(plan)
                    # Settle the tail of the window BEFORE any epoch
                    # accounting: a flagged in-flight step must roll the
                    # epoch back, never reach the history or the save.
                    drain_window()
                    jax.block_until_ready(state.params)
                    epoch_s = time.monotonic() - t_epoch
                    tok_s = (
                        n_tokens / max(epoch_s, 1e-9) if n_tokens else None
                    )
                    epoch_loss = float(jnp.stack(losses).mean())
                    history.append(epoch_loss)
                    rec = obs.recorder()
                    if rec is not None:
                        rec.record(
                            "span", "train.epoch", ts=ts_epoch, dur_s=epoch_s,
                            epoch=epoch, loss=epoch_loss,
                            tokens_per_s=round(tok_s, 1) if tok_s else None,
                        )
                    clock.goodput_mark()
                    if n_tokens:
                        # Comm attribution at the epoch fence (ISSUE 10):
                        # mean step wall vs the compute/comm rooflines →
                        # train.exposed_comm_s / train.comm_overlap_s.
                        # No-op off-TPU (no invented attribution).
                        from tpuflow.train.step import (
                            comm_attribution,
                            emit_comm_gauges,
                        )

                        per_step_tokens = cfg.batch_size * cfg.seq_len
                        n_steps = max(n_tokens // per_step_tokens, 1)
                        emit_comm_gauges(
                            comm_attribution(
                                epoch_s / n_steps,
                                tokens=per_step_tokens,
                                n_params=n_params,
                                accum_steps=cfg.accum_steps,
                                fsdp_world=fsdp_world,
                                overlapped=bool(overlap),
                            )
                        )
                    # Held-out validation: token-level loss -> perplexity
                    # over EVERY test window (padded tail masked out). The
                    # best/retention policy keys on real val loss, matching
                    # the reference's save-best-on-val semantics
                    # (my_ray_module.py:190-201), not the train loss.
                    with obs.span("train.validation", epoch=epoch):
                        val_loss = run_validation(
                            state,
                            val_loader,
                            eval_step,
                            place=lambda x: jax.device_put(
                                x, batch_sharding
                            ),
                        )
                    ppl = math.exp(min(val_loss, 30.0))
                    epoch_records.append(
                        {
                            "epoch": epoch,
                            "train_loss": epoch_loss,
                            "val_loss": val_loss,
                            "ppl": ppl,
                            "tokens_per_s": round(tok_s, 1)
                            if tok_s
                            else None,
                        }
                    )
                    rate = f" ({tok_s:.0f} tok/s)" if tok_s else ""
                    log(
                        f"[gpt] epoch {epoch}: loss={epoch_loss:.4f} "
                        f"val_loss={val_loss:.4f} ppl={ppl:.2f}{rate}"
                    )
                    payload = {
                        "step": state.step,
                        "params": state.params,
                        "opt_state": state.opt_state,
                    }
                    if cfg.ema_decay > 0.0:
                        payload["ema_params"] = state.ema_params
                    mgr.save(
                        int(state.step),
                        payload,
                        metrics={
                            "val_loss": val_loss,
                            "train_loss": epoch_loss,
                            "ppl": ppl,
                        },
                        # Epoch boundary: the next attempt resumes at the
                        # next epoch's head.
                        data_state={
                            "epoch": epoch + 1,
                            "batch_index": 0,
                            "seed": loader.seed,
                        },
                    )
                    if launch_attempt() > 0 or generation > 0:
                        # Retried attempt or re-formed elastic generation:
                        # commit eagerly so this epoch is durable before
                        # the crashing step reruns (see
                        # utils.preempt.launch_attempt — deferred commits
                        # livelock deterministic crashes; a post-reform
                        # gang replays abandoned steps for the same
                        # reason).
                        mgr.wait_until_finished()
                break
            except health_mod.TrainingDiverged:
                # Halt path: drain in-flight saves so the failing process
                # leaves only committed checkpoints behind.
                mgr.wait_until_finished()
                raise
            except health_mod._RollbackSignal as rb:
                # Divergence auto-rollback: restore the last crc-verified
                # checkpoint (handle_anomaly picked it) and replay from
                # there — the reverse of the in-run resume path above.
                # In-flight steps past the flagged one are discarded
                # along with the state they produced.
                window.clear()
                from_step = opt_step
                if monitor.cfg.lr_backoff != 1.0:
                    # LR backoff rides a rebuilt optimizer; the schedule
                    # lives inside the compiled update, so the new tx
                    # recompiles the step — acceptable for an event that
                    # is rare by construction (max_rollbacks bounds it).
                    lr_scale *= monitor.cfg.lr_backoff
                    tx = dataclasses.replace(
                        cfg,
                        learning_rate=cfg.learning_rate * lr_scale,
                    ).optimizer()
                tmpl = {
                    "step": state.step,
                    "params": state.params,
                    "opt_state": state.opt_state,
                }
                if cfg.ema_decay > 0.0:
                    tmpl["ema_params"] = state.params
                restored = mgr.restore(rb.target, abstract_state=tmpl)
                jax.block_until_ready(restored)
                state = TrainState(
                    step=restored["step"],
                    apply_fn=model.apply,
                    params=restored["params"],
                    tx=tx,
                    opt_state=restored["opt_state"],
                    batch_stats={},
                    ema_params=restored.get("ema_params", {}),
                )
                opt_step = int(state.step)
                start_epoch, pending_skip = _resume_cursor(
                    (mgr._read_meta(rb.target) or {}).get("data_state"),
                    opt_step, cfg.steps_per_epoch, cfg.epochs, loader.seed,
                )
                # Rewind every history the replayed epochs will re-append
                # to — the save-per-epoch invariant keeps them in step.
                mgr.rewind_history(rb.target)
                history = history[:start_epoch]
                epoch_records = epoch_records[:start_epoch]
                obs.event(
                    "health.rollback",
                    step=rb.target, from_step=from_step,
                    detector=rb.anomaly.kind, lr_scale=lr_scale,
                    rollbacks=monitor.rollbacks,
                )
                log(
                    f"[gpt] health rollback: {rb.anomaly.describe()} → "
                    f"restored verified step {rb.target} "
                    f"(epoch {start_epoch}, lr_scale {lr_scale:g})"
                )
            except (_membership.MeshReform, Preempted):
                raise
            except Exception as e:
                # A collective died mid-epoch. In an elastic gang a dead
                # peer's sockets close instantly and this is the FIRST
                # place the survivor notices — classify against the
                # supervisor's re-form plan before giving up; a genuine
                # error (or a non-elastic gang) re-raises unchanged.
                if not elastic:
                    raise
                plan = _membership.reform_after_failure(e)
                if plan is None:
                    raise
                window.clear()
                mgr.abandon_pending()
                mgr.close()
                raise _membership.MeshReform(plan) from e
        if profile is not None:
            profile.close()
        mgr.wait_until_finished()
        result = GptTrainResult(
            checkpoint=mgr.checkpoint(),
            loss_history=history,
            metrics_history=epoch_records,
        )
        mgr.close()
        if cfg.sample_tokens > 0:
            result.sample = _sample_greedy(cfg, model, state.params, log)
    return result


def _sample_greedy(cfg, model, params, log) -> list[int]:
    """Demonstrate the LM inference surface on the trained model: greedy
    KV-cache decode (tpuflow.infer.generate), sharded params and all —
    GSPMD handles the gather under jit."""
    import jax.numpy as jnp

    from tpuflow.infer import generate, render_tokens

    # Byte-level corpora get a readable prompt ("The ") and a text
    # rendering of the sample; token corpora print ids.
    byte_level = cfg.dataset == "lm_text"
    prompt = (
        jnp.asarray([list(b"The ")], jnp.int32)
        if byte_level
        else jnp.zeros((1, 4), jnp.int32)
    )
    toks = generate(
        model, params, prompt,
        max_new_tokens=cfg.sample_tokens, temperature=0.0,
    )
    sample = [int(t) for t in toks[0]]
    log(
        "[gpt] greedy sample: "
        f"{render_tokens(sample, byte_level=byte_level)!r}"
    )
    return sample


def _train_pipeline(
    cfg: GptTrainConfig, ckpt_dir: str, resume_checkpoint, log
) -> GptTrainResult:
    """GPipe pipeline-parallel training over a ('data','stage') mesh:
    scan-stacked blocks shard by layer slice (tpuflow.parallel.pipeline),
    grads flow through the microbatch schedule, checkpoints carry the
    pipeline-sharded state (the raw format's shard-ownership rule covers
    any sharding, so resume works unchanged)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpuflow import dist
    from tpuflow.ckpt import CheckpointManager, restore_from_handle
    from tpuflow.models.gpt2 import GPT2
    from tpuflow.parallel import gpt2_pipeline_loss, gpt2_pipeline_shardings

    model_cfg = cfg.model_config()
    mesh = dist.make_mesh({"data": cfg.data_axis, "stage": cfg.stage_axis})
    log(
        f"[gpt] pipeline mesh {dict(mesh.shape)}, "
        f"microbatches={cfg.microbatches}"
    )
    model = GPT2(model_cfg)
    tx = cfg.optimizer()
    loss_fn = gpt2_pipeline_loss(
        model_cfg, mesh=mesh, n_microbatches=cfg.microbatches
    )

    def init_params(rng):
        return model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]

    with mesh:
        p_shapes = jax.eval_shape(init_params, jax.random.PRNGKey(0))
        shardings = gpt2_pipeline_shardings(mesh, p_shapes)
        # Optimizer state mirrors the params tree (mu/nu under the same
        # 'h' paths → 'stage'-sharded; counts are scalars → replicated),
        # so the same path rule shards it.
        opt_shape = jax.eval_shape(tx.init, p_shapes)
        opt_shardings = gpt2_pipeline_shardings(mesh, opt_shape)
        start_step = 0

        mgr = CheckpointManager(
            ckpt_dir, max_to_keep=2, save_dtype=cfg.ckpt_dtype or None
        )
        # In-run resume after a retry/requeue: continue from this run's
        # newest committed step (cross-run handles still win).
        resume_step = (
            mgr.latest_step() if resume_checkpoint is None else None
        )
        # One abstract template serves resume AND divergence rollback —
        # both restore the same pipeline-sharded {step, params, opt_state}.
        abstract = {
            "step": jax.ShapeDtypeStruct((), jnp.int32),
            "params": jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=sh
                ),
                p_shapes,
                shardings,
            ),
            "opt_state": jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=sh
                ),
                opt_shape,
                opt_shardings,
            ),
        }
        if resume_checkpoint is None and resume_step is None:
            # Params born sharded: init is jitted with the pipeline
            # shardings as out_shardings, so no host ever materializes
            # the full replicated tree. Resumes skip this entirely — the
            # restore produces every leaf (materializing random weights
            # just to overwrite them doubled resume wall time).
            # Donation audit (ISSUE 4): these one-shot resharding jits
            # (and the eager-restore device_puts below) deliberately do
            # NOT donate their inputs — the restore fallback path may
            # re-read `restored` after a corrupt-shard retry, and a
            # donated-then-freed source would alias whatever the next
            # dispatch-ahead step wrote into that buffer.
            params = jax.jit(init_params, out_shardings=shardings)(
                jax.random.PRNGKey(0)
            )
            opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)
        else:
            if resume_checkpoint is not None:
                restored = restore_from_handle(
                    resume_checkpoint, abstract_state=abstract
                )
            else:
                restored = mgr.restore(resume_step, abstract_state=abstract)
            # Normalize placement: scalar/replicated leaves may come back
            # single-device; device_put onto the target shardings is
            # idempotent for already-placed shards.
            params = jax.device_put(restored["params"], shardings)
            opt_state = jax.device_put(restored["opt_state"], opt_shardings)
            start_step = int(restored["step"])
            log(
                "[gpt] pipeline-sharded state restored"
                + (" (in-run resume)" if resume_step is not None else "")
            )
        mgr.prewarm({"params": params, "opt_state": opt_state})

        # Donated params/opt_state: old and new state never coexist in HBM
        # (matches make_train_step's donate pattern; safe because mgr.save
        # snapshots device buffers synchronously before its async writer
        # starts, and the loop rebinds both every step). Dispatch-ahead
        # audit (ISSUE 4): with N steps in flight the only live
        # references are the loop's current params/opt_state bindings
        # (each step's donated inputs were the PREVIOUS step's outputs,
        # rebound before the next dispatch) and the window's loss/hstats
        # entries — fresh, never-donated output buffers. Nothing else
        # may retain the donated trees between dispatches. A factory so
        # the divergence LR backoff can rebuild the step around a
        # rescaled tx.
        def make_pp_step(tx):
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def pp_step(params, opt_state, x, y):
                from tpuflow.train.optim import health_stats

                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                updates, opt_state = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                return (
                    new_params,
                    opt_state,
                    loss,
                    health_stats(loss, grads, updates, new_params),
                )

            return pp_step

        pp_step = make_pp_step(tx)

        loader, _ = _loaders(cfg, model_cfg.vocab_size)
        data_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data")
        )
        history = []
        if resume_step is not None:
            # Seed continuity across the retry: each committed epoch's
            # train loss was recorded as its save metric.
            history += [
                m["val_loss"]
                for m in mgr._metrics_history
                if "val_loss" in m
            ]
        global_step = start_step
        start_epoch = 0
        resume_skip = 0
        if resume_step is not None:
            start_epoch, resume_skip = _resume_cursor(
                (mgr._read_meta(resume_step) or {}).get("data_state"),
                start_step, cfg.steps_per_epoch, cfg.epochs, loader.seed,
            )
            log(
                f"[gpt] pipeline in-run resume from step {start_step} "
                f"→ epoch {start_epoch}"
                + (
                    f" (replaying from batch {resume_skip})"
                    if resume_skip
                    else ""
                )
            )
        from tpuflow import obs
        from tpuflow.data.loader import prefetch_to_device
        from tpuflow.obs import goodput as goodput_mod
        from tpuflow.obs import health as health_mod
        from tpuflow.train.step import (
            DispatchWindow,
            StepClock,
            dispatch_depth,
        )

        monitor = health_mod.HealthMonitor.from_env()
        profile = health_mod.ProfileWindow.from_env()
        lr_scale = 1.0
        fault_env = bool(knobs.raw("TPUFLOW_FAULT"))
        from tpuflow.dist import membership as _membership

        elastic = _membership.enabled()
        clock = StepClock()
        # Rolling-MFU feed (see the FSDP leg): 6·N over the pipeline-
        # sharded params, set after the clock reset the live ledger.
        goodput_mod.live().set_model_flops_per_token(
            6.0
            * sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
        )
        # Dispatch-ahead window, same contract as the FSDP leg: fences
        # (the float() copies in settle) trail dispatch by up to depth-1
        # steps; every drain point below settles to a step boundary.
        window = DispatchWindow(dispatch_depth())
        obs.gauge("train.dispatch_depth", float(window.depth))
        obs.event(
            "train.remat_policy",
            policy=active_remat_policy(model_cfg),
            comm_overlap=False,  # the pipeline schedule microbatches itself
            accum_steps=1,
        )

        def settle(entry) -> None:
            step_no, loss, hstats, tokens, timed = entry
            if monitor is not None or clock.recording:
                nf = bool(float(hstats["nonfinite"]))
                m_loss = float(loss)
                m_gn = float(hstats["grad_norm"])
                if clock.recording:
                    if timed:
                        clock.step_done(tokens=tokens, step=step_no)
                    clock.health_done(
                        loss=m_loss,
                        grad_norm=m_gn,
                        update_norm=float(hstats["update_norm"]),
                        param_norm=float(hstats["param_norm"]),
                        nonfinite=nf,
                    )
                if monitor is not None:
                    anomaly = monitor.observe(
                        step_no, m_loss, m_gn, nonfinite=nf
                    )
                    if anomaly is not None:
                        target = health_mod.handle_anomaly(
                            monitor, anomaly, mgr
                        )
                        raise health_mod._RollbackSignal(target, anomaly)
            else:
                jax.block_until_ready(loss)
                if timed:
                    clock.step_done(tokens=tokens, step=step_no)

        def drain_window() -> None:
            for entry in window.drain():
                settle(entry)

        def drain_preempt() -> None:
            drain_window()
            payload = {
                "step": jnp.int32(global_step),
                "params": params,
                "opt_state": opt_state,
            }
            data_state = loader.state_dict(cursor["batch"])
            if mgr.latest_step() != global_step:
                if emergency_save_advised():
                    # Closing grace window: fastest-tier commit, upload
                    # skipped (see the FSDP leg's drain).
                    mgr.emergency_save(
                        global_step, payload, data_state=data_state
                    )
                else:
                    mgr.save(
                        global_step, payload, metrics={},
                        data_state=data_state,
                    )
                    mgr.wait_until_finished()
            mgr.close()
            raise Preempted(f"drained checkpoint at step {global_step}")

        def drain_reform_fallback(plan) -> None:
            # Pipeline state shards by LAYER slice over 'stage': a lost
            # member removes a pipeline STAGE, which no data-axis reshard
            # can absorb — elastic re-form degrades to the preemption
            # requeue here (the relaunched attempt re-forms at
            # generation 0 over whatever capacity remains). A grow fence
            # (everyone alive) drains and commits first; after a loss the
            # stranded save is abandoned (its commit collectives would
            # only raise again).
            if plan.reason == "grow":
                drain_preempt()  # commits + raises Preempted
            window.clear()
            mgr.abandon_pending()
            mgr.close()
            raise Preempted(
                f"mesh re-form (generation {plan.generation}) requeues "
                "the pipeline leg"
            )

        def place_batch(b):
            # Prefetch-thread placement onto the pipeline's 'data' axis.
            return {
                "x": jax.device_put(b["x"], data_sharding),
                "y": jax.device_put(b["y"], data_sharding),
            }

        first = True
        pending_skip = resume_skip
        cursor = {"batch": 0}
        while True:
            try:
                for epoch in range(start_epoch, cfg.epochs):
                    loader.set_epoch(epoch)
                    cursor["batch"] = pending_skip
                    if pending_skip:
                        loader.skip_batches(pending_skip)
                        pending_skip = 0
                    losses = []
                    clock.reset()
                    for batch in prefetch_to_device(
                        loader, mesh, keys=("x", "y"), place=place_batch
                    ):
                        if fault_env:
                            from tpuflow.testing import faults

                            poison = faults.grad_poison(global_step + 1)
                            if poison is not None:
                                params = jax.tree_util.tree_map(
                                    lambda p: p * poison, params
                                )
                        if profile is not None:
                            profile.maybe_start(global_step + 1)
                        params, opt_state, loss, hstats = pp_step(
                            params,
                            opt_state,
                            batch["x"],
                            batch["y"],
                        )
                        dist.step_fence(loss)
                        losses.append(loss)
                        tokens = int(batch["y"].size)
                        global_step += 1
                        if first:
                            jax.block_until_ready(loss)
                            compile_s = clock.compile_done(
                                mode="pipeline"
                            )
                            if compile_s is not None:
                                from tpuflow.obs import device as _devmod

                                _devmod.note_jit_program(
                                    "train.pp_step",
                                    pp_step,
                                    (params, opt_state, batch["x"],
                                     batch["y"]),
                                    compile_s=compile_s,
                                )
                            first = False
                            settle((global_step, loss, hstats, 0, False))
                        else:
                            for entry in window.push(
                                (global_step, loss, hstats, tokens, True)
                            ):
                                settle(entry)
                        cursor["batch"] += 1
                        if profile is not None:
                            drain_window()
                            profile.maybe_stop(global_step)
                        if fault_env:
                            from tpuflow.testing import faults

                            faults.step_boundary(global_step)
                        if preemption_requested():
                            drain_preempt()
                        if elastic:
                            plan = _membership.pending_reform()
                            if plan is not None:
                                drain_reform_fallback(plan)
                    drain_window()
                    jax.block_until_ready(params)
                    epoch_loss = float(jnp.stack(losses).mean())
                    history.append(epoch_loss)
                    clock.goodput_mark()
                    log(
                        f"[gpt] pipeline epoch {epoch}: "
                        f"loss={epoch_loss:.4f}"
                    )
                    mgr.save(
                        global_step,
                        {
                            "step": jnp.int32(global_step),
                            "params": params,
                            "opt_state": opt_state,
                        },
                        metrics={"val_loss": epoch_loss},
                        data_state={
                            "epoch": epoch + 1,
                            "batch_index": 0,
                            "seed": loader.seed,
                        },
                    )
                    if launch_attempt() > 0 or elastic:
                        # Retried attempt (or an elastic gang, where a
                        # re-form may strand a deferred commit): eager
                        # commit for monotonic progress (see
                        # utils.preempt.launch_attempt).
                        mgr.wait_until_finished()
                break
            except health_mod.TrainingDiverged:
                mgr.wait_until_finished()
                raise
            except health_mod._RollbackSignal as rb:
                window.clear()
                from_step = global_step
                if monitor.cfg.lr_backoff != 1.0:
                    lr_scale *= monitor.cfg.lr_backoff
                    tx = dataclasses.replace(
                        cfg,
                        learning_rate=cfg.learning_rate * lr_scale,
                    ).optimizer()
                    pp_step = make_pp_step(tx)
                restored = mgr.restore(rb.target, abstract_state=abstract)
                params = jax.device_put(restored["params"], shardings)
                opt_state = jax.device_put(
                    restored["opt_state"], opt_shardings
                )
                global_step = int(restored["step"])
                start_epoch, pending_skip = _resume_cursor(
                    (mgr._read_meta(rb.target) or {}).get("data_state"),
                    global_step, cfg.steps_per_epoch, cfg.epochs,
                    loader.seed,
                )
                mgr.rewind_history(rb.target)
                history = history[:start_epoch]
                obs.event(
                    "health.rollback",
                    step=rb.target, from_step=from_step,
                    detector=rb.anomaly.kind, lr_scale=lr_scale,
                    rollbacks=monitor.rollbacks,
                )
                log(
                    f"[gpt] pipeline health rollback: "
                    f"{rb.anomaly.describe()} → restored verified step "
                    f"{rb.target} (epoch {start_epoch})"
                )
        if profile is not None:
            profile.close()
        mgr.wait_until_finished()
        result = GptTrainResult(
            checkpoint=mgr.checkpoint(),
            loss_history=history,
            metrics_history=[
                {"epoch": i, "train_loss": l} for i, l in enumerate(history)
            ],
        )
        mgr.close()
    return result
