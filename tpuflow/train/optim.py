"""Optimizer factory: schedules + clipping + weight decay in one place.

The reference's optimizer story is one line (``torch.optim.SGD(lr,
momentum=0.9)``, my_ray_module.py:142); real LM training needs the standard
trio — linear warmup into cosine decay, global-norm gradient clipping, and
decoupled weight decay — composed the optax way (pure gradient
transformations chained into one ``tx`` the jitted step applies). One
factory keeps every flow/trainer on the same recipe and keeps the schedule
inside the compiled update (the step counter lives in the optimizer state,
so there is no host-side LR bookkeeping to checkpoint separately).
"""

from __future__ import annotations

import optax


def make_schedule(
    learning_rate: float,
    *,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    schedule: str = "constant",
    final_scale: float = 0.1,
):
    """LR schedule: 'constant' | 'cosine' | 'linear', with optional warmup.

    ``decay_steps`` counts AFTER warmup; ``final_scale`` is the floor as a
    fraction of the peak (cosine/linear end there, then hold).
    """
    if schedule == "constant":
        main = optax.constant_schedule(learning_rate)
    elif schedule == "cosine":
        main = optax.cosine_decay_schedule(
            learning_rate, max(decay_steps, 1), alpha=final_scale
        )
    elif schedule == "linear":
        main = optax.linear_schedule(
            learning_rate, learning_rate * final_scale, max(decay_steps, 1)
        )
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    if warmup_steps > 0:
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, learning_rate, warmup_steps),
                main,
            ],
            boundaries=[warmup_steps],
        )
    return main


def make_optimizer(
    learning_rate: float,
    *,
    optimizer: str = "adamw",
    weight_decay: float = 1e-4,
    momentum: float = 0.9,
    grad_clip_norm: float | None = None,
    warmup_steps: int = 0,
    decay_steps: int = 0,
    schedule: str = "constant",
    final_scale: float = 0.1,
) -> optax.GradientTransformation:
    """'adamw' | 'sgd' | 'adafactor' | 'lion' with optional global-norm
    clipping and LR schedule.

    Clipping runs BEFORE the optimizer update (the standard order: the
    update direction is computed from the clipped gradient). Defaults
    (constant schedule, no warmup, no clip, optax's weight decay) produce
    an optimizer whose state tree is IDENTICAL to plain
    ``optax.adamw(lr)`` / ``optax.sgd(lr, momentum)`` — checkpoints
    written before this factory existed keep restoring. Any real schedule
    adds a step-counter leaf to the state; resume with the same flags.
    """
    if schedule == "constant" and warmup_steps == 0:
        # Plain float: optax skips scale_by_schedule, keeping the state
        # pytree bit-compatible with pre-factory checkpoints.
        sched: float | optax.Schedule = learning_rate
    else:
        sched = make_schedule(
            learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
            schedule=schedule,
            final_scale=final_scale,
        )
    if optimizer == "adamw":
        tx = optax.adamw(sched, weight_decay=weight_decay)
    elif optimizer == "sgd":
        tx = optax.sgd(sched, momentum=momentum)
    elif optimizer == "adafactor":
        # The TPU-classic memory-efficient optimizer: factored second
        # moments store O(rows+cols) per matrix instead of Adam's O(n)
        # (a 355M-param model's optimizer state drops from ~2.8 GiB to
        # ~4 MiB of factored stats + the params) — the standard choice
        # when optimizer HBM, not FLOPs, bounds model size.
        tx = optax.adafactor(
            sched, weight_decay_rate=weight_decay or None
        )
    elif optimizer == "lion":
        # Sign-momentum optimizer (Chen et al. 2023): one momentum buffer
        # (half Adam's state), well-behaved in bf16; typical LR ~3-10x
        # lower than AdamW's for the same config.
        tx = optax.lion(sched, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")
    if grad_clip_norm is not None:
        if grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {grad_clip_norm}")
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx


def health_stats(loss, grads, updates, new_params) -> dict:
    """On-device numerics telemetry for one train step (ISSUE 3).

    Piggybacks on the same fused ``optax.global_norm`` reduction the
    clipping path already runs: ``updates`` is the delta ``tx.update``
    already produced (no re-derivation), and the NaN/Inf flag is one
    scalar check on norms that exist anyway — NaN/Inf in ANY leaf
    propagates into its global norm, so no per-leaf scan runs. All four
    scalars ride the step's output and materialize with the loss at the
    step fence — no extra device syncs on the hot path.
    """
    import jax.numpy as jnp

    grad_norm = optax.global_norm(grads)
    update_norm = optax.global_norm(updates)
    param_norm = optax.global_norm(new_params)
    nonfinite = jnp.logical_not(
        jnp.isfinite(loss) & jnp.isfinite(grad_norm) & jnp.isfinite(update_norm)
    ).astype(jnp.float32)
    return {
        "grad_norm": grad_norm,
        "update_norm": update_norm,
        "param_norm": param_norm,
        "nonfinite": nonfinite,
    }
