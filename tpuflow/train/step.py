"""Jitted train/eval steps — the innermost hot loop.

TPU-native replacement for the reference's per-minibatch loop
(my_ray_module.py:153-175): one compiled ``train_step(state, batch, rng)``
where the data-parallel gradient all-reduce is emitted by GSPMD over ICI
(because the batch is sharded on the 'data' mesh axis while params are
replicated or FSDP-sharded) — there is no DDP wrapper and no explicit
collective call, matching the reference's encapsulation of NCCL behind
``prepare_model`` (my_ray_module.py:135).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Callable

import flax
import jax
import jax.numpy as jnp
from flax.training import train_state

from tpuflow import obs
from tpuflow.models.losses import accuracy, cross_entropy_loss
from tpuflow.obs import device as _device
from tpuflow.obs import goodput as _goodput
from tpuflow.obs import profcap as _profcap
from tpuflow.utils.heartbeat import beat as _heartbeat

# Preemption surface of the train layer (ISSUE 2): gang_exec installs the
# SIGTERM handler; the epoch loops check ``preemption_requested()`` at step
# boundaries, drain + commit a final checkpoint, and raise ``Preempted`` —
# which gang_exec converts into REQUEUE_EXIT_CODE so the flow supervisor
# reruns the step without consuming the @retry budget.
from tpuflow.utils.preempt import (  # noqa: F401  (re-exported API)
    REQUEUE_EXIT_CODE,
    Preempted,
    clear_preemption,
    emergency_save_advised,
    grace_remaining_s,
    install_sigterm_handler,
    preemption_requested,
    request_preemption,
)

# Elastic gang (ISSUE 7): the mesh re-form control-flow signal mirrors
# the rollback/preemption surface above — raised at step fences when the
# supervisor announced a new mesh generation, handled by the generation
# loops (train.gpt, Trainer.fit).
from tpuflow.dist.membership import MeshReform  # noqa: F401  (re-export)
from tpuflow.utils import knobs


def dispatch_depth(default: int = 2) -> int:
    """Resolve the dispatch-ahead window depth (ISSUE 4).

    ``TPUFLOW_DISPATCH_DEPTH`` steps may be in flight on the accelerator
    before the host materializes the oldest step's scalars (loss, health
    numerics): the hot loops push each step's outputs into a
    :class:`DispatchWindow` and only settle — ``float()`` the device
    scalars, which is the true fence — once the window is full. Depth 1
    reproduces the old settle-every-step loop exactly; the default of 2
    keeps one step queued behind the executing one, so host-side work
    (batch placement, telemetry, the health monitor) overlaps device
    compute instead of serializing with it.

    Values < 1 clamp to 1; a malformed value falls back to ``default``
    (the loop must never die on a typo'd env var mid-provisioning).

    Platform note: on the serialized host-CPU dev platform the loops
    still ``dist.step_fence`` each step at dispatch (XLA:CPU's
    collective rendezvous kills the process when more than one
    collective program is in flight on a starved host — see
    ``dist.serialize_steps``), so there the window only defers the
    host-side accounting; on accelerators the window IS the only
    per-step synchronization.
    """
    env = knobs.raw("TPUFLOW_DISPATCH_DEPTH")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, default)


class DispatchWindow:
    """Bounded dispatch-ahead bookkeeping for a fenced step loop.

    Pure host-side bookkeeping (no jax dependency): the loop ``push``es
    one opaque entry per dispatched step; once ``depth`` entries are
    pending, ``push`` returns the oldest entries (the matured ones) for
    the caller to settle — the caller's settle function does the actual
    fence (``float()`` on a device scalar blocks until that step's
    program finished, which transitively bounds the in-flight window).
    ``drain()`` matures everything pending (epoch end, preemption drain,
    pre-checkpoint barrier); ``clear()`` abandons pending entries
    without settling them (divergence rollback: the in-flight steps are
    being discarded along with the state they produced).

    Why the caller settles instead of a callback: settle raises —
    health anomalies unwind the epoch loop via ``_RollbackSignal`` — and
    the raise must happen in the loop's own try block, not inside a
    helper frame holding half-consumed state.
    """

    def __init__(self, depth: int = 1):
        self.depth = max(1, int(depth))
        self._pending: collections.deque = collections.deque()

    def push(self, entry) -> list:
        """Queue one dispatched step; return entries due for settling
        (oldest first). With depth N, the entry pushed for step i
        matures when step i+N-1 is pushed — depth 1 returns every entry
        immediately (the settle-every-step loop)."""
        self._pending.append(entry)
        out = []
        while len(self._pending) >= self.depth:
            out.append(self._pending.popleft())
        return out

    def drain(self) -> list:
        """Mature every pending entry (oldest first)."""
        out = list(self._pending)
        self._pending.clear()
        return out

    def clear(self) -> None:
        """Abandon pending entries WITHOUT settling (rollback path)."""
        self._pending.clear()

    def __len__(self) -> int:
        return len(self._pending)


class StepClock:
    """Per-step wall-time telemetry for a fenced step loop.

    The epoch loops (tpuflow.train.gpt) fence every step (dist.step_fence),
    so host-side monotonic deltas between fences ARE per-step wall time —
    this clock turns them into the unified telemetry stream: a
    ``train.compile`` span for the cold first step (jit trace + compile +
    first execution, the part that must be split out or it poisons every
    throughput number), a ``train.step_s`` histogram observation per
    steady-state step, and a ``train.tokens`` counter for tokens/sec
    derivation. Every method is a no-op when telemetry is disabled — the
    loop pays one attribute check per step, nothing else (pinned by
    tests/test_obs.py overhead guard).
    """

    def __init__(self):
        self._on = obs.enabled()
        # Anomaly-triggered profiler capture (ISSUE 15): None unless
        # TPUFLOW_PROF_TRIGGER — the disarmed hot path is one
        # `is not None` check per fenced step (pinned by the
        # tests/test_obs.py overhead guard).
        self._cap = _profcap.maybe_from_env()
        track = self._on or self._cap is not None
        self._last = time.monotonic() if track else 0.0
        self._t0 = self._last
        self._ts0 = time.time() if self._on else 0.0
        self._steps = 0
        if self._on:
            # One clock per train leg: restart the live goodput ledger
            # (tpuflow.obs.goodput) the export endpoint serves, so
            # /metrics reflects THIS leg, not a previous run in the same
            # process.
            _goodput.live().reset()

    def reset(self) -> None:
        """Restart the clock (epoch boundary / after the compile fence)."""
        if self._on or self._cap is not None:
            self._last = time.monotonic()

    def compile_done(self, **attrs) -> float | None:
        """The cold first step just fenced: record it as train.compile.
        Returns the compile wall seconds when recording (the train legs
        hand it to the device ledger's compile-fence entry), else None."""
        _heartbeat()
        if not (self._on or self._cap is not None):
            return None
        now = time.monotonic()
        dur = now - self._t0
        self._last = now
        if not self._on:
            return None
        rec = obs.recorder()
        if rec is not None:
            rec.record(
                "span", "train.compile", ts=self._ts0,
                dur_s=dur, **attrs,
            )
        _goodput.live().note_compile(dur)
        _goodput.emit_gauges()
        # First post-compile HBM reading (ISSUE 15): the compiled
        # programs' buffers just landed — the most informative poll of
        # the run (self-disabling off-TPU).
        _device.maybe_emit_hbm(force=True)
        return dur

    def step_done(self, tokens: int = 0, step: int | None = None) -> None:
        """A steady-state step just fenced: record its wall time. Also
        stamps this gang member's heartbeat — the step fence is the
        liveness signal the gang supervisor watches (no-op outside a
        supervised gang), now carrying the CURRENT step number so a stall
        report can say where the member stopped."""
        _heartbeat(step)
        cap = self._cap
        if not self._on:
            if cap is not None:
                now = time.monotonic()
                cap.observe_step(now - self._last, step)
                self._last = now
            return
        now = time.monotonic()
        dur = now - self._last
        obs.histogram("train.step_s", dur)
        if tokens:
            obs.counter("train.tokens", tokens)
        self._last = now
        _goodput.live().note_step(dur, tokens=tokens, step=step)
        if cap is not None:
            # Median+MAD step-time spike detector (ISSUE 15); the same
            # call advances a live capture's bound.
            cap.observe_step(dur, step)
        self._steps += 1
        if self._steps % 32 == 0:
            # Periodic goodput-so-far gauges: cheap (three buffered
            # records), and the event stream then carries the
            # incremental ledger even for runs that die mid-epoch.
            _goodput.emit_gauges()
            # HBM gauges ride the same cadence, throttled further by
            # TPUFLOW_DEVICE_POLL_S (one bool check off-TPU).
            _device.maybe_emit_hbm()

    def goodput_mark(self) -> None:
        """Epoch-fence hook: flush the goodput-so-far gauges so every
        epoch boundary has a fresh incremental ledger reading."""
        if self._on:
            _goodput.emit_gauges()
            _device.maybe_emit_hbm()

    @property
    def recording(self) -> bool:
        """Whether this clock's run records telemetry — the loops use it
        to decide, once per step, whether to host-copy the numerics
        scalars for the ``health.*`` gauges."""
        return self._on

    def health_done(
        self,
        *,
        loss: float,
        grad_norm: float,
        update_norm: float,
        param_norm: float,
        nonfinite: bool,
    ) -> None:
        """Record the fenced step's on-device numerics (ISSUE 3). The
        scalars were computed inside the jitted step and materialized by
        the fence the loop already paid — this only copies four floats
        into the event buffer. No-op when telemetry is disabled."""
        if nonfinite and self._cap is not None:
            # Direct capture trigger (ISSUE 15): the numerics went bad;
            # the trace shows what the device was doing when they did.
            self._cap.note_nonfinite()
        if not self._on:
            return
        obs.gauge("health.loss", loss)
        obs.gauge("health.grad_norm", grad_norm)
        obs.gauge("health.update_norm", update_norm)
        obs.gauge("health.param_norm", param_norm)
        if nonfinite:
            obs.counter("health.nonfinite")
        _goodput.live().note_health(loss, grad_norm, nonfinite)


class TrainState(train_state.TrainState):
    """Flax TrainState: {step, params, opt_state} pytree + static apply_fn/tx.

    The pytree leaves are exactly the checkpoint payload of the reference
    ({epoch, model_state_dict, optimizer_state_dict}, my_ray_module.py:183-185)
    plus the step counter. ``batch_stats`` carries BatchNorm running
    statistics for models that have them (ResNets); it is an empty dict
    otherwise. Under pjit/GSPMD the batch-mean reduction is over the GLOBAL
    (logically unsharded) batch, so the running statistics are identical on
    every replica by construction — stronger than torch DDP's per-replica
    stats (reference my_ray_module.py:135), where replicas silently diverge.
    The checkpoint therefore stores the one true global statistic
    (pinned by tests/test_train_step.py::test_batchnorm_stats_are_global).
    """

    batch_stats: Any = flax.struct.field(default_factory=dict)
    # Exponential moving average of params (empty dict = EMA off). Enable
    # with ``with_ema(state)`` + ``make_train_step(ema_decay=...)``; the
    # averaged weights ride the state pytree, so they checkpoint/restore
    # with everything else and evaluate via ``state.replace(params=
    # state.ema_params)``.
    ema_params: Any = flax.struct.field(default_factory=dict)


def create_train_state(model, rng, sample_input, tx) -> TrainState:
    """Initialize params and optimizer state (reference my_ray_module.py:131,
    141-142: NeuralNetwork() + SGD(lr, momentum=0.9))."""
    variables = model.init(rng, sample_input, train=False)
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        tx=tx,
    )


def _variables(state: TrainState, params):
    v = {"params": params}
    if state.batch_stats:
        v["batch_stats"] = state.batch_stats
    return v


def per_worker_batch_size(global_batch_size: int, num_workers: int) -> int:
    """Per-shard batch = global // num_workers, floor division exactly as the
    reference computes it (my_ray_module.py:230)."""
    per = global_batch_size // num_workers
    if per < 1:
        raise ValueError(
            f"global batch {global_batch_size} too small for {num_workers} workers"
        )
    return per


def with_ema(state: TrainState) -> TrainState:
    """Seed EMA tracking: the averaged weights start as a COPY of the
    current params (distinct buffers — aliasing them would donate the same
    buffer through two pytree leaves on the first donated step). Pair with
    ``make_train_step(ema_decay=...)``."""
    return state.replace(
        ema_params=jax.tree_util.tree_map(jnp.copy, state.params)
    )


def comm_overlap_enabled(default: bool = True) -> bool:
    """Resolve the comm/compute-overlap knob (ISSUE 10).

    ``TPUFLOW_COMM_OVERLAP=0`` disables the per-microbatch gradient
    reduce-scatter inside the accumulation scan (and the async-collective
    XLA flags ``dist.maybe_enable_async_collectives`` would stage);
    anything else — including unset — leaves it on. The knob only
    changes programs where it can matter: ``make_train_step`` applies it
    when ``accum_steps > 1`` AND the caller passed ``grad_shardings``.
    """
    return knobs.raw("TPUFLOW_COMM_OVERLAP", "1").lower() not in (
        "0", "false", "off",
    )


def make_train_step(
    loss_fn: Callable = cross_entropy_loss,
    *,
    donate: bool = True,
    accum_steps: int = 1,
    ema_decay: float | None = None,
    grad_shardings: Any = None,
    comm_overlap: bool | None = None,
) -> Callable:
    """Build the jitted SPMD train step.

    The returned ``fn(state, batch, rng) -> (state, metrics)`` is traced once
    and compiled by XLA (static shapes; the Python epoch loop only feeds
    sharded batches, SURVEY.md §3.5). ``rng`` is folded with ``state.step`` so
    dropout masks differ per step while the traced function stays pure.

    ``accum_steps > 1`` enables gradient accumulation: the batch's leading
    axis splits into that many equal microbatches, a ``lax.scan`` runs
    forward+backward per microbatch (peak activation memory drops by the
    same factor), averaged gradients feed ONE optimizer update — numerically
    identical to the full-batch step for mean losses (pinned by
    tests/test_train_step.py). The scan is a compiler-friendly loop: one
    trace, static shapes, grads carried in place.

    Comm/compute overlap (ISSUE 10): with ``grad_shardings`` (the
    per-leaf param shardings of the FSDP leg) and ``accum_steps > 1``,
    each microbatch's gradient is pinned to those shardings INSIDE the
    scan body (``with_sharding_constraint``), which makes GSPMD emit the
    gradient reduce-scatter per microbatch — right behind that
    microbatch's backward — instead of one deferred reduction after the
    whole scan. With the async-collective XLA flags staged
    (``dist.maybe_enable_async_collectives``), the TPU scheduler then
    hides each bucket's DCN/ICI time behind the NEXT microbatch's
    backward compute; the accumulator also stays SHARDED, cutting its
    HBM footprint by the fsdp world size. The bucketing is the gradient
    tree itself: each leaf is one collective, issued the moment its
    microbatch produces it. ``comm_overlap=None`` resolves
    ``TPUFLOW_COMM_OVERLAP`` (default on); the sequential scan is
    recovered with ``TPUFLOW_COMM_OVERLAP=0``, and tests pin the two
    programs' losses against each other
    (tests/test_train_step.py::test_comm_overlap_scan_matches_sequential).

    Donation audit (ISSUE 4, dispatch-ahead): argument 0 (the state) is
    donated — XLA reuses its buffers for the new state, so the OLD state
    must never be touched after the call. The hot loops honor this by
    (a) rebinding ``state`` before the next dispatch and (b) keeping
    only the step's *outputs* (the metrics dict) alive in the
    :class:`DispatchWindow` while up to ``dispatch_depth()`` steps are
    in flight; batches and rng are NOT donated, so the prefetch thread's
    placed batches stay valid however late the step executes (pinned by
    tests/test_train_step.py donation-safety tests).
    """

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if ema_decay is not None and not 0.0 < ema_decay < 1.0:
        raise ValueError(
            f"ema_decay must be in (0, 1), got {ema_decay} (>= 1 freezes or "
            "diverges the average)"
        )
    if comm_overlap is None:
        comm_overlap = comm_overlap_enabled()
    overlap_active = (
        comm_overlap and grad_shardings is not None and accum_steps > 1
    )

    def _pin_grads(tree):
        # One with_sharding_constraint per gradient leaf: the per-
        # microbatch reduce-scatter "bucket" issue points (overlap path).
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, tree, grad_shardings
        )

    def train_step(state: TrainState, batch, rng):
        base_rng = jax.random.fold_in(rng, state.step)
        has_stats = bool(state.batch_stats)

        def compute_loss(params, batch_stats, mb, dropout_rng):
            # 'losses' collects auxiliary objectives the model sows (e.g. the
            # MoE load-balance loss); models without any sow leave it empty.
            mutable = ["losses"] + (["batch_stats"] if has_stats else [])
            variables = {"params": params}
            if has_stats:
                variables["batch_stats"] = batch_stats
            logits, updates = state.apply_fn(
                variables,
                mb["x"],
                train=True,
                rngs={"dropout": dropout_rng},
                mutable=mutable,
            )
            from tpuflow.models.losses import sum_sown_losses

            loss = loss_fn(logits, mb["y"]) + sum_sown_losses(updates)
            return loss, (logits, updates)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

        if accum_steps == 1:
            (loss, (logits, updates)), grads = grad_fn(
                state.params, state.batch_stats, batch, base_rng
            )
            acc = accuracy(logits, batch["y"])
            new_stats = updates.get("batch_stats") if has_stats else None
        else:
            n_rows = jax.tree_util.tree_leaves(batch)[0].shape[0]
            if n_rows % accum_steps:
                raise ValueError(
                    f"batch of {n_rows} rows does not split into "
                    f"accum_steps={accum_steps} equal microbatches"
                )
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape(
                    accum_steps, x.shape[0] // accum_steps, *x.shape[1:]
                ),
                batch,
            )

            def body(carry, inp):
                gsum, lsum, asum, stats = carry
                mb, idx = inp
                (l, (logits, updates)), g = grad_fn(
                    state.params, stats, mb, jax.random.fold_in(base_rng, idx)
                )
                if overlap_active:
                    # Pin THIS microbatch's gradients to the param
                    # shardings: GSPMD reduce-scatters them here, inside
                    # the scan body, where the async scheduler can slide
                    # the collective behind the next microbatch's
                    # backward — instead of one exposed reduction after
                    # the scan. The carried sum is then sharded too.
                    g = _pin_grads(g)
                carry = (
                    jax.tree_util.tree_map(jnp.add, gsum, g),
                    lsum + l,
                    asum + accuracy(logits, mb["y"]),
                    updates["batch_stats"] if has_stats else stats,
                )
                return carry, None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            if overlap_active:
                zeros = _pin_grads(zeros)
            (gsum, lsum, asum, new_stats), _ = jax.lax.scan(
                body,
                (zeros, 0.0, 0.0, state.batch_stats),
                (micro, jnp.arange(accum_steps)),
            )
            # Equal microbatches: the mean of microbatch means IS the
            # full-batch mean, for the loss and its gradient alike.
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                gsum,
                state.params,
            )
            loss = lsum / accum_steps
            acc = asum / accum_steps
        import optax

        # Explicit tx.update (what TrainState.apply_gradients wraps): the
        # produced ``updates`` tree feeds the health telemetry below
        # without a second optimizer pass or a params diff.
        updates, new_opt_state = state.tx.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        if has_stats:
            new_state = new_state.replace(batch_stats=new_stats)
        if ema_decay is not None:
            if not state.ema_params:
                raise ValueError(
                    "ema_decay is set but the state carries no ema_params; "
                    "seed them with tpuflow.train.with_ema(state)"
                )
            new_state = new_state.replace(
                ema_params=jax.tree_util.tree_map(
                    lambda e, p: e * ema_decay + (1.0 - ema_decay) * p,
                    state.ema_params,
                    new_state.params,
                )
            )
        # Pre-clip global gradient norm plus the rest of the on-device
        # numerics telemetry (update/param norms, fused NaN/Inf flag) —
        # tiny fused reductions, noise next to the backward pass; the
        # HealthMonitor and the health.* gauges read these post-fence.
        from tpuflow.train.optim import health_stats

        metrics = {
            "loss": loss,
            "accuracy": acc,
            **health_stats(loss, grads, updates, new_params),
        }
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def run_validation(state, loader, eval_step, *, place=None) -> float:
    """Mean per-element loss over a (possibly pad_tail) eval loader.

    The loader's per-row mask is broadcast to the label shape (so LM
    batches mask whole padded rows of tokens), every batch runs through the
    jitted ``eval_step``, and the masked sums accumulate host-side.
    ``place`` maps host arrays onto devices (default ``jnp.asarray``; pass
    a sharded ``device_put`` for mesh execution). One implementation shared
    by the training flows' per-epoch validation and the eval flows."""
    import numpy as np

    if place is None:
        place = jnp.asarray
    tot = cnt = 0.0
    for b in loader:
        batch = {"x": place(b["x"]), "y": place(b["y"])}
        mask = b.get("mask")
        if mask is not None:
            if mask.shape != b["y"].shape:
                mask = np.broadcast_to(mask[:, None], b["y"].shape)
            batch["mask"] = place(np.ascontiguousarray(mask, np.float32))
        m = eval_step(state, batch)
        tot += float(m["loss_sum"])
        cnt += float(m["count"])
    return tot / max(cnt, 1.0)


def make_eval_step(loss_fn: Callable = cross_entropy_loss) -> Callable:
    """Build the jitted eval step for the full validation pass
    (reference my_ray_module.py:162-175).

    Returns per-batch ``{loss_sum, num_correct, count}`` so the caller can
    accumulate across fixed-shape batches, honoring a ``mask`` entry (1 for
    real rows, 0 for tail padding — SURVEY.md §7 hard-part 5: XLA needs
    static shapes, so ragged tails are padded and masked out).
    """

    def eval_step(state: TrainState, batch):
        logits = state.apply_fn(
            _variables(state, state.params), batch["x"], train=False
        )
        labels = batch["y"]
        per_row = -jnp.take_along_axis(
            jax.nn.log_softmax(logits), labels[..., None], axis=-1
        )[..., 0]
        correct = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(labels.shape, jnp.float32)
        return {
            "loss_sum": jnp.sum(per_row * mask),
            "num_correct": jnp.sum(correct * mask),
            "count": jnp.sum(mask),
        }

    return jax.jit(eval_step)


# ---------------------------------------------- comm/compute attribution
# Aggregate ICI bandwidth per chip (GB/s, approximate public figures),
# matched against jax.devices()[0].device_kind like the goodput ledger's
# bf16-peak table. The denominator of the comm roofline below — an
# ATTRIBUTION model, not a measurement, so round numbers are fine.
_ICI_GBPS = (
    ("v6 lite", 800.0),
    ("v6lite", 800.0),
    ("v6e", 800.0),
    ("v5 lite", 400.0),
    ("v5lite", 400.0),
    ("v5e", 400.0),
    ("v5p", 1200.0),
    ("v5", 1200.0),
    ("v4", 300.0),
)


def _ici_gbps() -> float | None:
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return None
        kind = dev.device_kind.lower()
        return next((v for k, v in _ICI_GBPS if k in kind), 400.0)
    except Exception:
        return None


def comm_attribution(
    step_s: float,
    *,
    tokens: int,
    n_params: int,
    accum_steps: int = 1,
    fsdp_world: int = 1,
    overlapped: bool = True,
) -> dict | None:
    """Roofline attribution of one step's wall time into compute vs
    exposed communication (ISSUE 10): the numbers behind the
    ``train.exposed_comm_s`` / ``train.comm_overlap_s`` gauges and the
    bench train leg's ``exposed_comm_s`` record.

    This is a MODEL, stated as bounds, not a device measurement (XLA
    fuses the collectives into the step program; the host cannot time
    them separately without a profiler capture):

    - ``ideal_compute_s`` = 6·N FLOPs/token × tokens ÷ (bf16 peak ×
      devices) — the same estimate the rolling-MFU gauge uses.
    - ``exposed_comm_s`` = max(0, step_s − ideal_compute_s): every
      second the step spent NOT at peak compute. An UPPER bound on
      exposed communication (memory stalls and pipeline bubbles charge
      here too — attributing them to comm keeps the overlap claim
      conservative).
    - ``ideal_comm_s``: the FSDP step's collective volume at aggregate
      ICI bandwidth — per microbatch a param all-gather for fwd and one
      for the (remat) bwd, plus a gradient reduce-scatter per microbatch
      when overlapped (once per step when sequential: overlap trades
      (accum−1) extra grad reductions for hideability), each moving
      4 bytes × N × (w−1)/w per device.
    - ``comm_overlap_s`` = max(0, ideal_comm_s − exposed_comm_s): a
      LOWER bound on the comm time hidden behind compute.

    Returns None off-TPU (no peak table — an invented attribution would
    be noise) and with ``fsdp_world <= 1`` sets the comm terms to 0
    (single-shard: nothing to gather or scatter).
    """
    from tpuflow.obs import goodput as _gp

    peak = _gp._peak_flops_per_device()
    if peak is None or step_s <= 0.0 or n_params <= 0:
        return None
    import jax

    ndev = max(jax.device_count(), 1)
    ideal_compute_s = 6.0 * n_params * tokens / (peak * ndev)
    exposed = max(0.0, step_s - ideal_compute_s)
    ideal_comm_s = 0.0
    ici = _ici_gbps()
    if fsdp_world > 1 and ici:
        frac = (fsdp_world - 1) / fsdp_world
        bytes_per_pass = 4.0 * n_params * frac
        ag_passes = 2 * max(accum_steps, 1)
        rs_passes = max(accum_steps, 1) if overlapped else 1
        ideal_comm_s = (ag_passes + rs_passes) * bytes_per_pass / (
            ici * 1e9
        )
    return {
        "ideal_compute_s": ideal_compute_s,
        "ideal_comm_s": ideal_comm_s,
        "exposed_comm_s": exposed,
        "comm_overlap_s": max(0.0, ideal_comm_s - exposed),
        "overlapped": bool(overlapped),
    }


def emit_comm_gauges(att: dict | None) -> None:
    """Publish a step's comm attribution onto the telemetry stream.
    No-op when the attribution is unavailable (off-TPU) or telemetry is
    disabled — the gauges only ever carry chip-grounded values."""
    if att is None or not obs.enabled():
        return
    obs.gauge("train.exposed_comm_s", round(att["exposed_comm_s"], 6))
    obs.gauge("train.comm_overlap_s", round(att["comm_overlap_s"], 6))
