"""Trainer runtime: the Ray-Train-shaped API over SPMD JAX.

Replaces TorchTrainer / ScalingConfig / RunConfig / CheckpointConfig /
ray.train.report / Result as the reference exercises them
(my_ray_module.py:216-251, 149, 177, 203-205):

- ``Trainer(train_loop_per_worker, train_loop_config, scaling_config,
  run_config).fit() → Result`` — same constructor shape.
- Worker-group launch becomes SPMD: the loop body runs **once per host
  process** (one per pod-slice host, gang-launched by the flow layer), and
  the "workers" of ScalingConfig are data-parallel shards on the device mesh.
  Collectives are emitted by XLA inside the jitted step, so the per-worker
  loop contains no communication code — the same encapsulation Ray Train
  gives the reference.
- ``get_context().report(metrics, state=...)`` collects per-epoch metrics and
  drives the async sharded CheckpointManager (retention + best/latest),
  replacing report()'s upload-to-storage_path.
- ``Result`` carries final metrics, the metrics history, and checkpoint
  *handles* (path + metadata, never tensors) for cross-run/flow handoff.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable

import jax

from tpuflow import dist, obs
from tpuflow.ckpt import Checkpoint, CheckpointManager
from tpuflow.utils.heartbeat import beat as _heartbeat
from tpuflow.utils import knobs
from tpuflow.utils.preempt import (
    Preempted,
    launch_attempt,
    preemption_requested,
)

logger = logging.getLogger("tpuflow.train")


@dataclasses.dataclass
class ScalingConfig:
    """↔ ray ScalingConfig(num_workers, use_gpu) (my_ray_module.py:240-243).

    ``num_workers``: data-parallel shard count; ``None``/-1 → every device.
    ``mesh_axes``: optional full mesh spec (e.g. {'data': 4, 'tensor': 2}) for
    beyond-DP layouts; overrides num_workers.
    ``dcn_mesh_axes``: optional DCN (cross-slice / cross-host) axes for a
    hybrid mesh — e.g. ``dcn_mesh_axes={'data': 2}`` with
    ``mesh_axes={'fsdp': 4}`` puts the gradient all-reduce across hosts
    and FSDP's per-layer collectives on ICI (dist.make_hybrid_mesh). When
    set without ``mesh_axes``, the per-slice devices land on 'fsdp'.
    """

    num_workers: int | None = None
    use_tpu: bool = True  # kept for config parity; devices come from jax
    mesh_axes: dict[str, int] | None = None
    dcn_mesh_axes: dict[str, int] | None = None
    rendezvous_timeout_s: float = 300.0  # ↔ all_nodes_started_timeout


@dataclasses.dataclass
class CheckpointConfig:
    """↔ ray CheckpointConfig(num_to_keep=2) (my_ray_module.py:222,236)."""

    num_to_keep: int | None = 2
    best_metric: str = "val_loss"
    best_mode: str = "min"
    async_save: bool = True
    # Reduced-precision checkpoints: 'bfloat16'/'float16' casts floating
    # leaves down on save (half the bytes, double the effective GB/s);
    # None = bit-exact. See CheckpointManager(save_dtype=...).
    save_dtype: str | None = None


@dataclasses.dataclass
class RunConfig:
    """↔ ray RunConfig(checkpoint_config, storage_path, verbose)
    (my_ray_module.py:235-239)."""

    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )
    verbose: int = 1


@dataclasses.dataclass
class Result:
    """↔ ray Result (my_ray_module.py:250-251; consumed at train_flow.py:71-77,
    eval_flow.py:42-49): metrics + checkpoint handles, JSON-serializable."""

    metrics: dict[str, Any]
    metrics_history: list[dict[str, Any]]
    checkpoint: Checkpoint | None
    best_checkpoint: Checkpoint | None
    path: str | None
    # The mesh the run actually trained on (axis -> size): the structural
    # proof consumers need to verify a topology ask (e.g. the hybrid
    # DCN x ICI layout) was honored, without scraping gang-worker logs.
    mesh_axes: dict[str, int] | None = None

    def to_json(self) -> dict:
        return {
            "metrics": self.metrics,
            "metrics_history": self.metrics_history,
            "checkpoint": self.checkpoint.to_json() if self.checkpoint else None,
            "best_checkpoint": (
                self.best_checkpoint.to_json() if self.best_checkpoint else None
            ),
            "path": self.path,
            "mesh_axes": self.mesh_axes,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Result":
        return cls(
            metrics=obj.get("metrics", {}),
            metrics_history=obj.get("metrics_history", []),
            checkpoint=(
                Checkpoint.from_json(obj["checkpoint"]) if obj.get("checkpoint") else None
            ),
            best_checkpoint=(
                Checkpoint.from_json(obj["best_checkpoint"])
                if obj.get("best_checkpoint")
                else None
            ),
            path=obj.get("path"),
            mesh_axes=obj.get("mesh_axes"),
        )


class TrainContext:
    """Per-worker context (↔ ray.train.get_context() + report()).

    ``world_size``: data-parallel shard count (my_ray_module.py:149 uses it
    for the batch split); ``world_rank``: this host process's index
    (my_ray_module.py:177 uses it for logging).
    """

    def __init__(self, mesh, run_config: RunConfig):
        self.mesh = mesh
        self.run_config = run_config
        self._reported: list[dict[str, Any]] = []
        self._manager: CheckpointManager | None = None
        # Training-health watch over reported metrics (ISSUE 3): custom
        # Trainer loops own their state, so in-process rollback is not
        # ours to do — instead a diverged report SKIPS the checkpoint
        # save (the last durable step stays clean) and raises
        # TrainingDiverged; the gang @retry machinery then relaunches
        # and resumes from that clean step — rollback by requeue.
        from tpuflow.obs.health import HealthMonitor

        self._health = HealthMonitor.from_env()
        if run_config.storage_path:
            cc = run_config.checkpoint_config
            self._manager = CheckpointManager(
                os.path.join(run_config.storage_path, "checkpoints"),
                max_to_keep=cc.num_to_keep,
                best_metric=cc.best_metric,
                best_mode=cc.best_mode,
                async_save=cc.async_save,
                save_dtype=cc.save_dtype,
            )

    def get_world_size(self) -> int:
        return dist.data_axis_size(self.mesh)

    def get_world_rank(self) -> int:
        return jax.process_index()

    @property
    def checkpoint_manager(self) -> CheckpointManager | None:
        return self._manager

    def prewarm_checkpoints(self, state) -> None:
        """Start background page-backing for this state's checkpoint files.

        Call right after building the train state: the pool warmup overlaps
        epoch-1 compute so even the run's FIRST ``report(state=...)`` save
        writes onto recycled pages (see RecyclePool.prewarm). Only this
        process's addressable shard bytes are counted.
        """
        if self._manager is not None:
            self._manager.prewarm(state)

    def report(
        self,
        metrics: dict[str, Any],
        *,
        state=None,
        step: int | None = None,
        data_state: dict[str, Any] | None = None,
    ) -> None:
        """Record epoch metrics; if ``state`` is given, save it as the epoch's
        checkpoint (async, sharded). ↔ ray.train.report(metrics, checkpoint)
        (my_ray_module.py:203-205). Acts as a gang barrier like the original.

        ``data_state``: optional loader cursor (epoch, batch index, shuffle
        seed — ``ShardedLoader.state_dict``) persisted in the checkpoint's
        metadata; a resumed attempt reads it back via ``latest_data_state``
        and skips exactly the consumed batches (deterministic mid-epoch
        resume, ISSUE 5).
        """
        from tpuflow.dist import membership as _membership

        # Elastic gang (ISSUE 7): the report IS this loop's step fence —
        # a pending mesh generation unwinds the loop here, BEFORE this
        # step's metrics/save land (the re-formed loop replays it). One
        # env lookup when not in an elastic gang.
        plan = _membership.pending_reform()
        if plan is not None:
            raise _membership.MeshReform(plan)
        metrics = {
            k: (float(v) if hasattr(v, "__float__") else v)
            for k, v in metrics.items()
        }
        self._reported.append(metrics)
        save_step = step if step is not None else len(self._reported)
        # Unified telemetry: every report lands in the run's event stream
        # beside the step spans (numeric metrics only — the event must
        # stay one JSON line).
        obs.event(
            "train.report",
            step=save_step,
            **{k: v for k, v in metrics.items()
               if isinstance(v, (int, float))},
        )
        # Custom loops have no StepClock: the report cadence feeds the
        # live goodput ledger the export endpoint serves (step number +
        # last loss; rates derive from the report fences).
        from tpuflow.obs import goodput as _goodput

        _goodput.live().note_report(
            save_step,
            loss=next(
                (
                    metrics[k]
                    for k in ("loss", "train_loss", "val_loss")
                    if isinstance(metrics.get(k), (int, float))
                ),
                None,
            ),
        )
        if self._health is not None:
            loss = next(
                (
                    metrics[k]
                    for k in ("loss", "train_loss", "val_loss")
                    if isinstance(metrics.get(k), float)
                ),
                None,
            )
            if loss is not None:
                gn = metrics.get("grad_norm")
                anomaly = self._health.observe(
                    save_step, loss,
                    gn if isinstance(gn, float) else None,
                )
                if anomaly is not None:
                    from tpuflow.obs.health import TrainingDiverged

                    if self._manager is not None:
                        self._manager.wait_until_finished()
                    raise TrainingDiverged(
                        anomaly,
                        hint="report skipped the checkpoint save; the "
                        "newest committed step is clean — a gang retry "
                        "resumes from it",
                    )
        try:
            if state is not None and self._manager is not None:
                self._manager.save(
                    save_step, state, metrics=metrics, data_state=data_state
                )
                if (
                    launch_attempt() > 0
                    or _membership.current_generation() > 0
                ):
                    # Retried attempt OR re-formed elastic generation:
                    # commit THIS step before returning to the loop (see
                    # launch_attempt — the async deferred commit would
                    # otherwise livelock a deterministic crash: the dying
                    # step never becomes the resume point. A post-reform
                    # gang replays for the same reason: a shrink abandons
                    # the stranded commit, so without eager banking a
                    # deterministic crasher re-fires on the replayed step
                    # and the gang oscillates shrink/grow until the
                    # resize budget forces the requeue fallback).
                    self._manager.wait_until_finished()
            if self.run_config.storage_path and jax.process_index() == 0:
                # Observability stream (SURVEY.md §5): one JSON line per
                # report, aggregated on process 0, appendable/tail-able
                # during the run.
                with open(
                    os.path.join(
                        self.run_config.storage_path, "metrics.jsonl"
                    ),
                    "a",
                ) as f:
                    f.write(
                        json.dumps(
                            {"step": save_step, "time": time.time(), **metrics}
                        )
                        + "\n"
                    )
            if self.run_config.verbose:
                logger.info("report[%d]: %s", len(self._reported), metrics)
            dist.barrier("report")
        except Exception as e:
            # A dead gang peer closes its sockets instantly, so the save
            # drain's commit collective or the report barrier raises here
            # within milliseconds of the loss. Give the supervisor — which
            # detects the death on its own poll — a bounded window to
            # announce the re-form; a genuine error re-raises unchanged.
            plan = _membership.reform_after_failure(e)
            if plan is None:
                raise
            raise _membership.MeshReform(plan) from e
        # Step boundary: stamp this member's liveness for the gang
        # supervisor, give the fault harness its injection point, then
        # honor a pending preemption — the state just saved above IS the
        # drain checkpoint, so committing it and raising is all that's
        # left (gang_exec turns Preempted into the requeue exit code).
        # The stamp carries the step so a stall report names WHERE the
        # member stopped, not just how stale the stamp is.
        _heartbeat(save_step)
        if knobs.raw("TPUFLOW_FAULT"):
            from tpuflow.testing import faults

            faults.step_boundary(save_step)
        if preemption_requested():
            if self._manager is not None:
                self._manager.wait_until_finished()
            raise Preempted(
                f"preempted; drained checkpoint at step {save_step}"
            )

    def latest_step(self) -> int:
        """Newest committed checkpoint step, 0 when none exists yet.

        The resume point for a retried or requeued gang attempt: the
        launcher passes the attempt through ``TPUFLOW_ATTEMPT`` and the
        loop continues from ``latest_step() + 1`` instead of step 0 (the
        manager already rebuilt the metrics history from the same
        checkpoint at construction)."""
        if self._manager is None:
            return 0
        return self._manager.latest_step() or 0

    def restore_latest(self, abstract_state=None):
        """Restore the newest committed checkpoint (crc-verified, local
        tier preferred, with fallback to the persistent copy and then the
        previous step on corruption); None when no checkpoint exists —
        start from scratch."""
        if self._manager is None or self._manager.latest_step() is None:
            return None
        return self._manager.restore(abstract_state=abstract_state)

    def latest_data_state(self) -> dict[str, Any] | None:
        """Loader cursor persisted with the newest committed checkpoint
        (``report(data_state=...)``), or None. Custom loops own their
        data pipeline, so mid-epoch replay is theirs to apply: feed the
        cursor back into ``ShardedLoader.set_epoch`` + ``skip_batches``
        before iterating the resumed epoch."""
        if self._manager is None:
            return None
        latest = self._manager.latest_step()
        if latest is None:
            return None
        try:
            return self._manager.restore_metadata(latest).get("data_state")
        except FileNotFoundError:
            return None

    def latest_metrics(self) -> dict[str, Any]:
        return self._reported[-1] if self._reported else {}


_ACTIVE_CONTEXT: TrainContext | None = None


def get_context() -> TrainContext:
    """↔ ray.train.get_context() (my_ray_module.py:149,177)."""
    if _ACTIVE_CONTEXT is None:
        raise RuntimeError("get_context() called outside a Trainer.fit() run")
    return _ACTIVE_CONTEXT


class Trainer:
    """↔ TorchTrainer(...).fit() (my_ray_module.py:244-250)."""

    def __init__(
        self,
        train_loop_per_worker: Callable[[dict], None],
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def _build_mesh(self):
        sc = self.scaling_config
        dist.initialize(timeout_s=sc.rendezvous_timeout_s)
        if sc.dcn_mesh_axes:
            import math

            ici = sc.mesh_axes
            if not ici:
                n_slices = math.prod(sc.dcn_mesh_axes.values())
                ndev = len(jax.devices())
                if ndev % n_slices:
                    raise ValueError(
                        f"dcn_mesh_axes {sc.dcn_mesh_axes} want {n_slices} "
                        f"slices but {ndev} devices don't divide evenly"
                    )
                ici = {"fsdp": ndev // n_slices}
            return dist.make_hybrid_mesh(sc.dcn_mesh_axes, ici)
        if sc.mesh_axes:
            return dist.make_mesh(sc.mesh_axes)
        ndev = len(jax.devices())
        n = sc.num_workers
        if n is None or n == -1:
            n = ndev
        from tpuflow.dist import membership as _membership

        if n != ndev and _membership.current_generation() > 0:
            # Re-formed elastic world (ISSUE 7): the world the caller
            # originally asked for no longer exists — the data-parallel
            # axis absorbs the resize. (Clamping, not erroring, is the
            # contract: a shrink must not crash the survivors into the
            # requeue path this machinery exists to avoid.)
            logger.info(
                "elastic generation %d: scaling num_workers %d → %d "
                "devices", _membership.current_generation(), n, ndev,
            )
            n = ndev
        if n > ndev:
            raise ValueError(f"num_workers={n} but only {ndev} devices present")
        if jax.process_count() > 1 and n != ndev:
            # A device subset on a multi-host gang would exclude some hosts'
            # devices: every process still enters the collectives (SPMD), so
            # the program would deadlock or crash inside XLA. Scale the gang
            # itself (fewer hosts / smaller slice) instead of slicing here.
            raise ValueError(
                f"num_workers={n} selects a subset of the {ndev} global "
                f"devices across {jax.process_count()} processes; device "
                "subsets are single-host only — use every gang device "
                "(num_workers=-1) or shrink the gang"
            )
        return dist.make_mesh({"data": n}, devices=jax.devices()[:n])

    def fit(self) -> Result:
        global _ACTIVE_CONTEXT
        # Persistent XLA compile cache (default-on; TPUFLOW_COMPILE_CACHE
        # =run keys it under this run's storage path): retried/requeued
        # attempts reload the compiled step instead of re-paying the
        # first-compile wall time. See dist.maybe_enable_compile_cache.
        dist.maybe_enable_compile_cache(run_dir=self.run_config.storage_path)
        # Live goodput + metrics endpoint (ISSUE 6): restart the ledger
        # for this fit, and serve /metrics + /status when opted in via
        # TPUFLOW_OBS_HTTP_PORT (member 0 only; one env lookup when off).
        from tpuflow.obs import export as _obs_export
        from tpuflow.obs import goodput as _goodput

        _goodput.live().reset()
        _obs_export.maybe_start_from_env()
        from tpuflow.dist import membership as _membership

        start = time.monotonic()
        reported_carry: list[dict[str, Any]] = []
        reforms = 0
        while True:
            mesh = self._build_mesh()
            ctx = TrainContext(mesh, self.run_config)
            if reported_carry:
                # Metrics reported by earlier generations of this fit —
                # the loop body restarted, the run did not.
                ctx._reported = list(reported_carry)
            _ACTIVE_CONTEXT = ctx
            reform_plan = None
            try:
                with obs.span(
                    "train.fit", workers=dist.data_axis_size(mesh),
                    generation=_membership.current_generation(),
                ), mesh:
                    try:
                        self.train_loop_per_worker(
                            dict(self.train_loop_config)
                        )
                    except _membership.MeshReform as rf:
                        reform_plan = rf.plan
                    except Exception as e:
                        # The loop body's own collective died (a peer's
                        # sockets close instantly): classify against the
                        # supervisor's plan before giving up.
                        plan = _membership.reform_after_failure(e)
                        if plan is None:
                            raise
                        reform_plan = plan
            finally:
                _ACTIVE_CONTEXT = None
                if (
                    reform_plan is None
                    and ctx.checkpoint_manager is not None
                ):
                    ctx.checkpoint_manager.wait_until_finished()
            if reform_plan is None:
                break
            # Mesh re-form (ISSUE 7): hand everything to the checkpoint,
            # tear the old world down, re-rendezvous as the new
            # generation, and re-enter the loop body — which resumes via
            # ctx.latest_step() exactly like a requeued attempt, at
            # step-fence cost instead of process-lifecycle cost.
            reforms += 1
            mgr = ctx.checkpoint_manager
            if mgr is not None:
                if reform_plan.reason == "grow":
                    # Every current member is alive at a grow fence: the
                    # deferred commit completes normally, so the grown
                    # gang resumes from the CURRENT step (the "emergency
                    # checkpoint if none is fresh" clause — report-driven
                    # loops save every step, so the drain commits it).
                    try:
                        mgr.wait_until_finished()
                    except Exception:
                        mgr.abandon_pending()
                else:
                    # A peer died mid-save: its shards will never arrive;
                    # the deferred commit is unfinishable. Abandon it and
                    # resume from the last FULLY committed step.
                    mgr.abandon_pending()
                mgr.close()
            reported_carry = list(ctx._reported)
            logger.info(
                "mesh re-form: generation %d (%s, %d members)",
                reform_plan.generation, reform_plan.reason,
                reform_plan.num_processes,
            )
            _membership.quiesce_and_reform(reform_plan)
        if self.run_config.verbose:
            logger.info(
                "fit() finished in %.1fs (%d reports)",
                time.monotonic() - start,
                len(ctx._reported),
            )
        mgr = ctx.checkpoint_manager
        latest = best = None
        if mgr is not None:
            if mgr.latest_step() is not None:
                latest = mgr.checkpoint()
            if mgr.best_step() is not None:
                best = mgr.checkpoint(best=True)
            mgr.close()
        # Metrics-history continuity across retries: a retried/requeued
        # attempt re-reported only its own steps, while the manager's
        # history — rebuilt from the latest committed checkpoint at
        # construction, extended by this attempt's saves — is continuous
        # from the first attempt's first save. Prefer it when it knows
        # more (reports without ``state=`` still fall back to _reported).
        # After a mesh re-form the manager's view is also the DEDUPED one:
        # a step reported right before the loss was replayed by the next
        # generation, so _reported can carry it twice.
        if mgr is not None and mgr._metrics_history and (
            reforms or len(mgr._metrics_history) > len(ctx._reported)
        ):
            metrics_history = [dict(m) for m in mgr._metrics_history]
        else:
            metrics_history = list(ctx._reported)
        return Result(
            metrics=ctx.latest_metrics(),
            metrics_history=metrics_history,
            checkpoint=latest,
            best_checkpoint=best,
            path=self.run_config.storage_path,
            mesh_axes={k: int(v) for k, v in mesh.shape.items()},
        )
