"""Shared utilities (file locking, timing, small helpers)."""

from tpuflow.utils.locking import FileLock

__all__ = ["FileLock"]
