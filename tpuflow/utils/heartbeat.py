"""Per-member gang heartbeats.

The gang supervisor (tpuflow.flow.runner) cannot tell a member that is
compiling from one that is deadlocked in a collective whose peer died —
both are silent. Heartbeat files break the tie: the runner hands every
member a private path via ``TPUFLOW_HEARTBEAT_FILE``; the member stamps it
(mtime touch) at cheap progress points — every fenced train step
(StepClock), every ``TrainContext.report`` — and the supervisor treats a
member whose *last* stamp is older than the stall timeout as hung,
killing the gang promptly instead of waiting out the flat rendezvous
deadline.

Contract: a member that never stamps is never monitored (arbitrary step
bodies owe no heartbeats, and a train loop's first compile is not judged
either — its first stamp lands only at the first fence), so the
supervisor only judges members that have demonstrably adopted the
protocol and then gone silent.
"""

from __future__ import annotations

import os
from tpuflow.utils import knobs


def heartbeat_file() -> str | None:
    return knobs.raw("TPUFLOW_HEARTBEAT_FILE") or None


def beat(step: int | None = None) -> None:
    """Stamp this member's heartbeat file; no-op outside a supervised gang.
    Never raises — a heartbeat must not fail the step it reports on.

    ``step`` (when the caller knows it — StepClock fences,
    TrainContext.report) is written as the file's CONTENT, so a stall
    report can name the member's last completed step, not just how old
    the stamp is; step-less beats keep the last stamped step."""
    path = heartbeat_file()
    if not path:
        return
    try:
        if step is None:
            with open(path, "a"):
                pass
        else:
            with open(path, "w") as f:
                f.write(str(int(step)))
        os.utime(path, None)
    except (OSError, TypeError, ValueError):
        return
    if knobs.raw("TPUFLOW_FAULT"):
        from tpuflow.testing import faults

        # After the stamp, so a stalled member shows exactly one beat and
        # then goes silent — the signature the supervisor must detect.
        faults.maybe_stall_heartbeat()
