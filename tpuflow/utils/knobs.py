"""Central registry of every ``TPUFLOW_*`` environment knob.

Eleven PRs grew ~90 env knobs across the tree, read by scattered
``os.environ`` calls and documented (or not) by hand-maintained README
tables. Each of those hand-kept agreements rots silently: a typo'd name
(``..._SERVE_PAGED`` misspelled ``..._SERVE_PAGE``) silently defaults, a
knob added in code never reaches the README, a README row outlives the
code that read it. This module is the single source of truth the Orbax
checkpoint-as-contract argument (PAPERS.md) asks for, applied to the
knob surface:

- **Declarations.** ``REGISTRY`` holds one :class:`Knob` per name —
  type, default, subsystem, the README runbook anchor that explains it,
  and a one-line doc. ``internal=True`` marks launcher/test plumbing
  (e.g. ``TPUFLOW_ATTEMPT``) that operators never set by hand; those are
  documented in a separate "internal plumbing" table instead of the
  operator tables.
- **Typed accessors.** :func:`raw` / :func:`is_set` /
  :func:`get_str` / :func:`get_int` / :func:`get_float` /
  :func:`get_bool` all refuse undeclared names with a ``KeyError`` — a
  typo'd knob READ dies at the call instead of silently defaulting.
  ``raw`` is the migration workhorse: it returns exactly what
  ``os.environ.get`` returned so call sites with bespoke parsing
  (malformed-value fallbacks pinned by tests) keep their behavior
  bit-for-bit while becoming registry-visible.
- **README sync.** ``python -m tpuflow.utils.knobs --markdown`` emits
  the per-subsystem knob tables; the README embeds them between
  ``KNOB TABLES`` markers and ``--check`` verifies the region matches
  byte-for-byte. Pass 1 of ``tools/tpulint.py`` runs the same check, so
  a registry edit without a README regen fails lint.

Import discipline: stdlib only (``os``/``dataclasses``/``sys``) — the
lint, the standalone tools, and ``flows/`` import this without paying a
jax import.
"""

from __future__ import annotations

import dataclasses
import os

_UNSET = object()

# README anchors (GitHub heading slugs) the tables link to.
_A_FLOW = "fault-tolerance-runbook"
_A_ELASTIC = "elastic-gang-runbook"
_A_CKPT = "checkpoint-durability-runbook"
_A_CKPT_SUB = "checkpoint-subsystem"
_A_HEALTH = "training-health-runbook"
_A_STEP = "step-pipeline--performance-runbook"
_A_SERVE = "serving-runbook"
_A_FLEET = "fleet-observability-runbook"
_A_ROUTER = "router--failover-runbook"
_A_TRACE = "distributed-tracing-runbook"
_A_DEVICE = "device-observatory-runbook"
_A_QUANT = "quantization-runbook"
_A_KV = "disaggregated-serving-runbook"
_A_ALERTS = "regression--alerting-runbook"
_A_OBS = "goodput--live-monitoring-runbook"
_A_OBS_BASE = "observability"
_A_SETUP = "setup"
_A_FSDP = "gpt-2-fsdp-fully-sharded-training"
_A_BENCH = "tests-and-benchmark"
_A_DEPLOY = "deploy--schedule--trigger"
_A_LINT = "static-analysis-runbook"


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared ``TPUFLOW_*`` environment knob."""

    name: str
    type: str  # str | int | float | bool | path | enum | list
    default: object  # parsed-type default; None = unset/off
    doc: str
    subsystem: str
    anchor: str  # README heading slug the runbook row links to
    internal: bool = False  # launcher/test plumbing, not an operator knob
    choices: tuple = ()  # for type == "enum"
    default_doc: str = ""  # table override when repr(default) reads badly

    @property
    def shown_default(self) -> str:
        if self.default_doc:
            return self.default_doc
        if self.default is None:
            return "unset"
        if self.type == "bool":
            return "1" if self.default else "0"
        return str(self.default)


def _k(*args, **kw) -> tuple[str, Knob]:
    knob = Knob(*args, **kw)
    return knob.name, knob


REGISTRY: dict[str, Knob] = dict(
    (
        # ----------------------------------------------------------- flow
        _k("TPUFLOW_HOME", "path", "~/.tpuflow",
           "root for run/artifact storage, deployments, and the default "
           "compile cache", "flow", _A_SETUP),
        _k("TPUFLOW_NAMESPACE", "str", None,
           "namespace runs are produced under (default `user:<login>`)",
           "flow", _A_SETUP),
        _k("TPUFLOW_N_PARALLEL", "int", 2,
           "gang width the example flows launch (processes forming one "
           "jax.distributed world)", "flow", _A_SETUP),
        _k("TPUFLOW_TOPOLOGY", "str", "v5e-8",
           "TPU topology the @kubernetes example flows request",
           "flow", _A_DEPLOY),
        _k("TPUFLOW_GANG_TIMEOUT", "float", 300.0,
           "gang member join/rendezvous timeout (s)", "flow", _A_FLOW),
        _k("TPUFLOW_KILL_GRACE_S", "float", 5.0,
           "supervisor SIGTERM → SIGKILL escalation grace for gang "
           "members", "flow", _A_FLOW),
        _k("TPUFLOW_MAX_REQUEUES", "int", 8,
           "preemption requeues a step may consume (requeues never touch "
           "the retry budget)", "flow", _A_FLOW),
        _k("TPUFLOW_STALL_TIMEOUT_S", "float", 600.0,
           "heartbeat age past which a gang member is declared stalled",
           "flow", _A_FLOW),
        _k("TPUFLOW_GANG_REJOIN", "bool", True,
           "relaunch crashed/preempted capacity so an elastic gang can "
           "re-grow", "flow", _A_ELASTIC),
        _k("TPUFLOW_ELASTIC", "bool", False,
           "1 = resize the mesh on member loss instead of "
           "requeue-the-world", "flow", _A_ELASTIC),
        _k("TPUFLOW_GANG_MIN_MEMBERS", "int", 2,
           "shrink floor; below it member loss falls back to the classic "
           "requeue (`@tpu(min_members=...)` overrides)", "flow",
           _A_ELASTIC),
        _k("TPUFLOW_REFORM_TIMEOUT_S", "float", 120.0,
           "mesh re-form announce → all-joined deadline; missing it → "
           "classic requeue", "flow", _A_ELASTIC),
        _k("TPUFLOW_REFORM_WAIT_S", "float", 10.0,
           "how long a survivor's failed collective waits for a re-form "
           "plan before the error is treated as real", "flow", _A_ELASTIC),
        _k("TPUFLOW_MAX_RESIZES", "int", 8,
           "resize budget per gang step (a deterministic crasher must "
           "not shrink forever)", "flow", _A_ELASTIC),
        _k("TPUFLOW_REJOIN_HOLD_S", "float", 10.0,
           "hold a relaunch until every survivor's post-shrink heartbeat "
           "reappears (or this many seconds pass)", "flow", _A_ELASTIC),
        _k("TPUFLOW_FORCE_CPU", "bool", False,
           "1 = pin gang subprocesses / platform probe to XLA:CPU "
           "virtual devices", "flow", _A_FLOW),
        _k("TPUFLOW_PREWARM_CACHE", "path", None,
           "prewarmed compile-cache dir gang members seed their cache "
           "from (rsync-style, missing entries only)", "flow", _A_STEP),
        # Launcher/member plumbing — stamped by the supervisor, read by
        # members; never set by operators.
        _k("TPUFLOW_ATTEMPT", "int", 0,
           "launch attempt number the supervisor stamps on each gang "
           "launch; keys goodput attempt lanes", "flow", _A_OBS,
           internal=True),
        _k("TPUFLOW_PROCESS_ID", "int", 0,
           "gang member rank the launcher assigns", "flow", _A_FLOW,
           internal=True),
        _k("TPUFLOW_NUM_PROCESSES", "int", 1,
           "gang world size the launcher assigns", "flow", _A_FLOW,
           internal=True),
        _k("TPUFLOW_COORDINATOR", "str", "127.0.0.1:42042",
           "jax.distributed coordinator address the launcher assigns",
           "flow", _A_FLOW, internal=True),
        _k("TPUFLOW_GANG_LOCAL_DEVICES", "int", 1,
           "virtual CPU devices per gang member on forced-CPU runs",
           "flow", _A_FLOW, internal=True),
        _k("TPUFLOW_MEMBERSHIP_DIR", "path", None,
           "elastic-gang rendezvous dir the supervisor assigns; its "
           "presence arms the membership runtime in members", "flow",
           _A_ELASTIC, internal=True),
        _k("TPUFLOW_FLOW", "str", None,
           "flow name the k8s manifest stamps into member pods", "flow",
           _A_DEPLOY, internal=True),
        _k("TPUFLOW_STEP", "str", None,
           "step name the k8s manifest stamps into member pods", "flow",
           _A_DEPLOY, internal=True),
        _k("TPUFLOW_RUN_ID", "str", None,
           "run id the k8s manifest stamps into member pods", "flow",
           _A_DEPLOY, internal=True),
        _k("TPUFLOW_REQUIREMENTS", "str", None,
           "pip requirements line the k8s manifest installs in member "
           "pods", "flow", _A_DEPLOY, internal=True),
        # ----------------------------------------------------------- dist
        _k("TPUFLOW_COMPILE_CACHE", "str", None,
           "persistent XLA compile cache: a directory, `run` "
           "(<run_dir>/compile_cache), or 0/off to disable (default: "
           "$TPUFLOW_HOME/compile_cache on accelerators)", "dist",
           _A_STEP, default_doc="$TPUFLOW_HOME/compile_cache"),
        _k("TPUFLOW_COMPILE_CACHE_CPU", "bool", False,
           "1 = force-enable the persistent compile cache on CPU "
           "(default off: the XLA:CPU AOT reloader can SIGABRT across "
           "machine-feature changes)", "dist", _A_STEP),
        _k("TPUFLOW_COMM_OVERLAP", "bool", True,
           "0 = disable comm/compute overlap (per-microbatch "
           "reduce-scatter in the accum scan + async-collective libtpu "
           "flags)", "dist", _A_STEP),
        _k("TPUFLOW_DCN_DATA", "int", 0,
           "N = put the worker mesh's data axis on the DCN (multi-slice) "
           "axis at width N", "dist", _A_FSDP),
        _k("TPUFLOW_PLATFORM_BACKEND", "str", None,
           "platform the probe/conftest pinned for this process (cpu | "
           "tpu); consumed pre-init by mesh/bench", "dist", _A_FLOW,
           internal=True),
        _k("TPUFLOW_PLATFORM_PROBED", "str", None,
           "cached platform-probe verdict (default | cpu) so respawns "
           "skip the subprocess probe", "dist", _A_FLOW, internal=True),
        # ---------------------------------------------------------- train
        _k("TPUFLOW_DISPATCH_DEPTH", "int", 2,
           "steps in flight before the hot loop settles the oldest "
           "step's scalars (1 = the old fully-synchronous loop)",
           "train", _A_STEP),
        _k("TPUFLOW_REMAT_POLICY", "enum", None,
           "remat selector for the train legs; env beats config, a typo "
           "fails at config time", "train", _A_STEP,
           choices=("full", "dots", "none"),
           default_doc="model preset's policy"),
        _k("TPUFLOW_TRAIN_MODE", "str", None,
           "`tpu` routes bench train legs through the real gang path",
           "train", _A_BENCH),
        _k("TPUFLOW_TRAIN_SMOKE", "bool", True,
           "0 = skip the on-TPU pre-bench train smoke", "bench",
           _A_BENCH),
        # ----------------------------------------------------------- data
        _k("TPUFLOW_DATA_DIR", "path", None,
           "dataset root (IDX/corpus files); unset → synthetic "
           "stand-ins", "data", _A_SETUP,
           default_doc="$TPUFLOW_HOME/data"),
        _k("TPUFLOW_TEXT_FILE", "path", None,
           "explicit LM corpus file (must exist — never degrades to "
           "synthetic)", "data", _A_SETUP),
        _k("TPUFLOW_FETCH", "bool", False,
           "1 = allow real dataset downloads (FileLock-guarded)",
           "data", _A_SETUP),
        _k("TPUFLOW_FETCH_BASE_URL", "str", None,
           "dataset download mirror override", "data", _A_SETUP),
        _k("TPUFLOW_SYNTH_TRAIN_N", "int", None,
           "synthetic dataset train-split size override", "data",
           _A_SETUP, default_doc="per dataset"),
        _k("TPUFLOW_SYNTH_TEST_N", "int", None,
           "synthetic dataset test-split size override", "data",
           _A_SETUP, default_doc="per dataset"),
        _k("TPUFLOW_PREFETCH_DEPTH", "int", 2,
           "batches buffered ahead by the device-put prefetch thread "
           "(0 = inline, no thread)", "data", _A_STEP),
        # ----------------------------------------------------------- ckpt
        _k("TPUFLOW_CKPT_FORMAT", "enum", "auto",
           "checkpoint format: native striped raw, orbax/ocdbt, or auto",
           "ckpt", _A_CKPT_SUB, choices=("auto", "raw", "orbax")),
        _k("TPUFLOW_CKPT_VERIFY", "bool", True,
           "0 = skip restore-side per-shard crc32 verification", "ckpt",
           _A_FLOW),
        _k("TPUFLOW_CKPT_MMAP", "bool", False,
           "1 = force mmap'd zero-copy restores process-wide (read-only "
           "consumers)", "ckpt", _A_CKPT_SUB),
        _k("TPUFLOW_CKPT_IO_RETRIES", "int", 4,
           "transient-failure retry budget per storage op (0 disables)",
           "ckpt", _A_CKPT),
        _k("TPUFLOW_CKPT_IO_BACKOFF_S", "float", 0.05,
           "base retry backoff (doubles per attempt, 50-100% jitter)",
           "ckpt", _A_CKPT),
        _k("TPUFLOW_CKPT_LOCAL_DIR", "path", None,
           "node-local fast checkpoint tier root (run-keyed; uploads to "
           "the persistent dir ride the saver thread)", "ckpt", _A_CKPT),
        _k("TPUFLOW_CKPT_LOCAL_KEEP", "int", 2,
           "newest committed steps kept in the local tier (oldest "
           "evicted first)", "ckpt", _A_CKPT),
        _k("TPUFLOW_WRITE_CONCURRENCY", "int", 0,
           "checkpoint shard-write pipeline width (0 = auto: 1 on "
           "memory-backed fs, else 4)", "ckpt", _A_CKPT_SUB),
        _k("TPUFLOW_IO_THREADS", "int", None,
           "explicit cap on total inflight checkpoint IO threads "
           "(wins over the restore floor of 4)", "ckpt", _A_CKPT_SUB,
           default_doc="min(cores, 16)"),
        _k("TPUFLOW_PREWARM_THREADS", "int", None,
           "page-backing prewarm threads (0 parks background prewarm, "
           ">=1 forces it)", "ckpt", _A_CKPT_SUB,
           default_doc="cores - 1"),
        _k("TPUFLOW_PREEMPT_GRACE_S", "float", None,
           "termination grace the preemption drain counts down from "
           "(gang launcher defaults it from TPUFLOW_KILL_GRACE_S; pods "
           "from terminationGracePeriodSeconds)", "ckpt", _A_CKPT),
        _k("TPUFLOW_PREEMPT_EMERGENCY_S", "float", 10.0,
           "remaining-grace threshold under which drains take the "
           "synchronous fastest-tier emergency save", "ckpt", _A_CKPT),
        # ------------------------------------------------------------ obs
        _k("TPUFLOW_OBS", "bool", True,
           "0 = disable the whole telemetry stream (recorder, ledger, "
           "export feed, flight ring)", "obs", _A_OBS_BASE),
        _k("TPUFLOW_OBS_MAX_BUFFERED", "int", 65536,
           "recorder buffer cap; overflow drops are counted and "
           "surfaced as a final obs.dropped event", "obs", _A_OBS_BASE),
        _k("TPUFLOW_OBS_FLIGHT_RING", "int", 256,
           "flight-recorder ring size (last events kept for the crash "
           "dump)", "obs", _A_OBS),
        _k("TPUFLOW_OBS_HTTP_PORT", "int", None,
           "live /metrics + /status export port on gang member 0 "
           "(0 = ephemeral; unset = no export)", "obs", _A_OBS),
        _k("TPUFLOW_OBS_HTTP_HOST", "str", "127.0.0.1",
           "live export bind host", "obs", _A_OBS),
        _k("TPUFLOW_OBS_DIR", "path", None,
           "telemetry dir a gang member inherits from the supervisor",
           "obs", _A_OBS_BASE, internal=True),
        _k("TPUFLOW_OBS_PROC", "int", None,
           "telemetry proc slot a gang member inherits from the "
           "supervisor", "obs", _A_OBS_BASE, internal=True),
        # --------------------------------------------------------- health
        _k("TPUFLOW_HEALTH", "bool", True,
           "0 = disable training-health monitoring entirely", "health",
           _A_HEALTH),
        _k("TPUFLOW_HEALTH_ROLLBACK", "bool", True,
           "0 = halt with a diagnostic instead of rolling back to the "
           "newest verified checkpoint", "health", _A_HEALTH),
        _k("TPUFLOW_HEALTH_NAN_BUDGET", "int", 1,
           "consecutive non-finite steps tolerated before an anomaly "
           "fires", "health", _A_HEALTH),
        _k("TPUFLOW_HEALTH_WINDOW", "int", 64,
           "rolling median/MAD loss window", "health", _A_HEALTH),
        _k("TPUFLOW_HEALTH_WARMUP", "int", 16,
           "observations before the spike detector judges", "health",
           _A_HEALTH),
        _k("TPUFLOW_HEALTH_SPIKE_MADS", "float", 12.0,
           "loss-spike threshold in MADs above the rolling median",
           "health", _A_HEALTH),
        _k("TPUFLOW_HEALTH_GRAD_MAX", "float", 0.0,
           "absolute grad-norm explosion threshold (0 = off)", "health",
           _A_HEALTH),
        _k("TPUFLOW_HEALTH_MAX_ROLLBACKS", "int", 2,
           "divergence rollbacks before halting anyway", "health",
           _A_HEALTH),
        _k("TPUFLOW_HEALTH_LR_BACKOFF", "float", 1.0,
           "peak-LR multiplier applied on each rollback (1.0 = off)",
           "health", _A_HEALTH),
        _k("TPUFLOW_PROFILE", "str", None,
           "`start:stop` step window wrapped in a jax.profiler trace",
           "health", _A_HEALTH),
        _k("TPUFLOW_PROFILE_DIR", "path", None,
           "profiler output dir outside a flow run", "health", _A_HEALTH),
        # ------------------------------------------------------------ ops
        _k("TPUFLOW_FLASH_MIN_SEQ", "int", None,
           "min seq length where flash attention beats XLA for "
           "forward+backward programs (auto-tuned; malformed → tuning "
           "file with a once-per-process warning)", "ops", _A_STEP,
           default_doc="2048 / tuned"),
        _k("TPUFLOW_FLASH_MIN_SEQ_FWD", "int", None,
           "min seq length where flash attention beats XLA for "
           "forward-only (decode prefill) programs", "ops", _A_STEP,
           default_doc="512 / tuned"),
        _k("TPUFLOW_FLASH_BWD", "enum", "fused",
           "flash backward implementation (split = the pre-fusion pair, "
           "regression reference)", "ops", _A_STEP,
           choices=("fused", "split", "blockwise")),
        _k("TPUFLOW_FLASH_LSE", "enum", None,
           "`compact` restores the small (non-lane-padded) backward "
           "residual for memory-bound remat-off configs", "ops", _A_STEP,
           choices=("compact",), default_doc="lane-padded"),
        _k("TPUFLOW_INT8_MATMUL", "enum", "auto",
           "int8 matmul impl: force xla/pallas, or auto-dispatch by "
           "shape", "quant", _A_QUANT,
           choices=("auto", "xla", "pallas")),
        _k("TPUFLOW_INT8_KERNEL_MIN_KN", "int", 262144,
           "min K*N weight-block size for the fused Pallas int8 kernel",
           "quant", _A_QUANT, default_doc="2^18"),
        # ---------------------------------------------------------- serve
        _k("TPUFLOW_SERVE", "bool", True,
           "0 = keep GenerationPredictor on the legacy per-batch-shape "
           "path", "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_SLOTS", "int", 8,
           "decode slots (the fixed batch of the persistent program)",
           "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_BUCKETS", "list", None,
           "comma prefill pad widths — the WHOLE prefill compile set",
           "serve", _A_SERVE,
           default_doc="power-of-two ladder → n_ctx - 1"),
        _k("TPUFLOW_SERVE_PREFILL_CHUNK", "int", None,
           "admission prefill chunk width (bounds peak attention "
           "memory)", "serve", _A_SERVE, default_doc="off"),
        _k("TPUFLOW_SERVE_DECODE_BLOCK", "int", 8,
           "tokens per decode dispatch (host syncs once per block)",
           "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_QUANT", "str", None,
           "1/fused_native/weight_only arms per-request int8 decode",
           "serve", _A_SERVE, default_doc="off"),
        _k("TPUFLOW_SERVE_PAGED", "bool", True,
           "0 = keep the contiguous per-slot cache rows (regression "
           "reference, kept one release)", "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_PAGE_SIZE", "int", 16,
           "tokens per KV page (must divide n_ctx; env values that "
           "don't degrade to a divisor)", "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_PAGES", "int", None,
           "page-pool size; page 0 is the reserved trash page", "serve",
           _A_SERVE, default_doc="slots * n_ctx / page_size + 1"),
        _k("TPUFLOW_SERVE_PREFIX_CACHE", "bool", True,
           "0 = disable shared-prefix page reuse", "serve", _A_SERVE),
        _k("TPUFLOW_SERVE_SPEC", "int", None,
           "K arms per-request speculative decode at draft length K "
           "(submit(speculative=False) opts a request out)", "serve",
           _A_SERVE, default_doc="off"),
        _k("TPUFLOW_SERVE_TRACE", "bool", True,
           "0 = disarm per-request lifecycle traces (serve.trace "
           "events + the request's host-side trace list)", "serve",
           _A_SERVE),
        _k("TPUFLOW_SERVE_ACCESS_LOG", "bool", True,
           "0 = disarm the per-request JSONL access log "
           "(obs/access.p*.jsonl, read by `serve-summary`)", "serve",
           _A_SERVE),
        _k("TPUFLOW_SERVE_SLO_TTFT_MS", "float", None,
           "declared TTFT SLO in ms; a violating request emits "
           "serve.slo_violation and bumps the violation counter",
           "serve", _A_SERVE, default_doc="off"),
        _k("TPUFLOW_SERVE_SLO_ITL_MS", "float", None,
           "declared inter-token-latency SLO in ms, checked per decode "
           "tick (tick wall / tokens committed)", "serve", _A_SERVE,
           default_doc="off"),
        # ------------------------------------------- kv (disaggregation)
        _k("TPUFLOW_SERVE_ROLE", "enum", "both",
           "serving phase this replica advertises: a prefill replica "
           "takes the router's ship hops, a decode replica takes "
           "admissions (both = classic colocated serving; every "
           "existing path stays byte-identical)", "kv", _A_KV,
           choices=("prefill", "decode", "both")),
        _k("TPUFLOW_KV_STORE_DIR", "path", None,
           "committed KVPageSet store (the prefill→decode shipping "
           "layer: one crc-manifested blob per page set, tmp+rename "
           "commit, torn sets never load)", "kv", _A_KV),
        _k("TPUFLOW_KV_HOST_MB", "float", 0.0,
           "host-DRAM spill-tier budget in MiB for evicted-but-"
           "matchable prefix pages (0 = tier off; eviction forgets "
           "pages exactly as before)", "kv", _A_KV),
        _k("TPUFLOW_KV_DISK_DIR", "path", None,
           "node-local disk spill tier (same kv_store commit protocol; "
           "rescanned at engine start, so hot prefixes survive a "
           "replica restart)", "kv", _A_KV),
        _k("TPUFLOW_KV_DISK_MB", "float", 0.0,
           "disk spill-tier LRU budget in MiB (0 = unbounded; trimmed "
           "by manifest mtime after each spill)", "kv", _A_KV),
        _k("TPUFLOW_KV_INDEX_MAX", "int", 4096,
           "bound on the digest→tier index (the eviction-forgets-"
           "digests fix: spilled prefixes stay findable for promotion "
           "and router affinity without unbounded host state)", "kv",
           _A_KV),
        _k("TPUFLOW_KV_SHIP_MIN_TOKENS", "int", 0,
           "router: prompts at least this long take a prefill-replica "
           "hop before decode placement (0 = ship hop off; any "
           "ship-hop failure falls back to local prefill)", "kv",
           _A_KV),
        # ---------------------------------------------------------- fleet
        _k("TPUFLOW_FLEET_REPLICAS", "list", None,
           "comma list of replica /status base URLs the fleet "
           "observatory polls; a hostname resolving to multiple A "
           "records (headless Service) expands to one replica per pod",
           "fleet", _A_FLEET, default_doc="unset"),
        _k("TPUFLOW_FLEET_REGISTRATION_DIR", "path", None,
           "file-based replica registry: every exporting process stamps "
           "replica-<id>.json here at export start, and the fleet "
           "observatory discovers the fleet from the directory",
           "fleet", _A_FLEET),
        _k("TPUFLOW_FLEET_POLL_S", "float", 5.0,
           "fleet poll cadence (also the base of the per-replica "
           "failure backoff)", "fleet", _A_FLEET),
        _k("TPUFLOW_FLEET_STALE_S", "float", 15.0,
           "seconds without a successful /status poll before a replica "
           "is marked stale (health score 0; fleet.replica_stale event)",
           "fleet", _A_FLEET),
        _k("TPUFLOW_FLEET_HIST_BUCKETS", "list", None,
           "comma TTFT/ITL histogram bucket upper edges in seconds "
           "(strictly increasing); every replica of a fleet must agree "
           "or its buckets cannot merge", "fleet", _A_FLEET,
           default_doc="1ms..10s ladder"),
        _k("TPUFLOW_FLEET_SNAPSHOT_PATH", "path", None,
           "append one fleet-snapshot JSON line per poll here "
           "(post-hoc analysis trail)", "fleet", _A_FLEET),
        _k("TPUFLOW_FLEET_REPLICA_ID", "str", None,
           "replica identity stamped into /status and the registration "
           "file (the serving Deployment sets it from the pod name; "
           "default host-pid)", "fleet", _A_FLEET, internal=True),
        # --------------------------------------------------------- router
        _k("TPUFLOW_ROUTER_PORT", "int", 8900,
           "front-door HTTP bind port (0 = ephemeral; the router is the "
           "fleet's single client-facing ingress)", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_HOST", "str", "127.0.0.1",
           "front-door HTTP bind host (0.0.0.0 for a cluster ingress)",
           "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_TARGET", "str", None,
           "replica discovery target: a registration dir or comma "
           "/status URL list (default: the fleet observatory's "
           "discovery knobs)", "router", _A_ROUTER,
           default_doc="fleet knobs"),
        _k("TPUFLOW_ROUTER_GATEWAY", "bool", True,
           "0 = serve_forever skips its replica-side /generate gateway "
           "(the fleet row stays status-only and the front door cannot "
           "forward to this replica); the gateway shares the step "
           "loop's lock and advertises its URL as generate_url in "
           "/status", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_TIMEOUT_S", "float", 30.0,
           "per-replica forward timeout (s): a stalled replica is "
           "indistinguishable from a slow one until this expires, then "
           "the request re-dispatches", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_RETRIES", "int", 3,
           "forward retry budget per request; exhaustion returns 503 to "
           "the client (bounded, never a hang)", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_BACKOFF_S", "float", 0.05,
           "exponential-backoff base slept before retry k "
           "(base * 2^(k-1), capped at 2s)", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_AFFINITY", "bool", True,
           "0 = disable prefix-affine routing (requests sharing a "
           "prompt prefix pin to the replica already holding those "
           "pages — the fleet-wide prefix cache)", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_HEDGE", "bool", False,
           "1 = the first retry after a forward failure fires "
           "immediately (no backoff sleep) — lower rerouted-tail "
           "latency at the cost of load on an already-degraded fleet",
           "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_MIN_HEALTH", "float", 0.25,
           "fleet health-score floor for routing eligibility (stale "
           "replicas score 0 and are never routable)", "router",
           _A_ROUTER),
        _k("TPUFLOW_ROUTER_TREND_DECAY", "float", 0.5,
           "balance-score multiplier per consecutive queue-growth poll "
           "(score = health * decay^trend): a replica falling behind "
           "its arrivals sheds new work geometrically", "router",
           _A_ROUTER),
        _k("TPUFLOW_ROUTER_QUEUE_TIMEOUT_S", "float", 60.0,
           "max seconds a request waits in the admission queue for "
           "fleet token budget before 503 (backpressure queues, never "
           "drops — this is the bound that keeps the queue finite)",
           "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_AUTOSCALE", "bool", False,
           "1 = arm the autoscale/replacement loop: dead replicas get "
           "prewarm_cache-seeded replacements, sustained occupancy/SLO "
           "pressure requests scale-up", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_AUTOSCALE_OCC", "float", 0.85,
           "fleet mean slot-occupancy threshold above which the "
           "autoscale loop requests one scale-up", "router", _A_ROUTER),
        _k("TPUFLOW_ROUTER_AUTOSCALE_SLO", "float", 0.05,
           "fleet SLO violation rate (violations/requests) above which "
           "the autoscale loop requests one scale-up", "router",
           _A_ROUTER),
        _k("TPUFLOW_ROUTER_AUTOSCALE_COOLDOWN_S", "float", 120.0,
           "minimum seconds between autoscale actions per replica slot "
           "(replacements must not flap faster than pods can start)",
           "router", _A_ROUTER),
        # --------------------------------------------------------- device
        _k("TPUFLOW_DEVICE_POLL_S", "float", 10.0,
           "HBM gauge poll cadence (s) at the fences the hot loops "
           "already pay (0 disables; backends without memory_stats "
           "disable themselves after the first probe)", "device",
           _A_DEVICE),
        _k("TPUFLOW_DEVICE_LEDGER", "bool", True,
           "0 = skip per-program compile/memory ledger collection "
           "(programs.json + device.program events) at the warmup/"
           "compile fences and serve start", "device", _A_DEVICE),
        _k("TPUFLOW_PROF_TRIGGER", "bool", False,
           "1 = arm anomaly-triggered profiler capture: step-time/ITL "
           "median+MAD spikes, SLO breaches, and nonfinite steps arm a "
           "bounded jax.profiler trace + device memory dump", "device",
           _A_DEVICE),
        _k("TPUFLOW_PROF_ZMADS", "float", 8.0,
           "anomaly trigger threshold in robust MADs above the rolling "
           "median (step-time and ITL detectors)", "device", _A_DEVICE),
        _k("TPUFLOW_PROF_COOLDOWN_S", "float", 300.0,
           "minimum seconds between triggered captures (the governor's "
           "rate bound)", "device", _A_DEVICE),
        _k("TPUFLOW_PROF_MAX_CAPTURES", "int", 3,
           "per-run triggered-capture cap; past it triggers are counted "
           "but suppressed", "device", _A_DEVICE),
        _k("TPUFLOW_PROF_TRACE_STEPS", "int", 2,
           "observations (train steps / decode ticks) one triggered "
           "trace spans before it stops — the capture's size bound",
           "device", _A_DEVICE),
        _k("TPUFLOW_PROF_DIR", "path", None,
           "triggered-capture output dir when telemetry is disabled "
           "(default <obs_dir>/profile)", "device", _A_DEVICE),
        # --------------------------------------------------------- alerts
        _k("TPUFLOW_REGISTRY_PATH", "path", None,
           "run-registry JSONL: every training run, serving run, and "
           "bench.py invocation appends one schema-versioned headline "
           "record here (unset = implicit run-end appends off; "
           "bench.py defaults to TPU_REGISTRY.jsonl beside its "
           "records)", "alerts", _A_ALERTS, default_doc="unset"),
        _k("TPUFLOW_REGISTRY_WINDOW", "int", 5,
           "trailing runs the trend/verdict median+MAD window spans",
           "alerts", _A_ALERTS),
        _k("TPUFLOW_REGISTRY_ZMADS", "float", 8.0,
           "regression threshold in robust MADs from the trailing "
           "median (the PR 15 detector idiom, host-side)",
           "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_SLO_BUDGET", "float", 0.01,
           "SLO violation-rate budget the burn-rate windows are "
           "measured against (violations / requests)", "alerts",
           _A_ALERTS),
        _k("TPUFLOW_ALERT_FAST_WINDOW_S", "float", 300.0,
           "fast burn-rate window (s); the page needs the fast AND "
           "slow windows both over budget", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_SLOW_WINDOW_S", "float", 3600.0,
           "slow burn-rate window (s) — proves the burn is sustained, "
           "not one bad minute", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_HBM_HEADROOM", "float", 0.08,
           "free-HBM fraction floor (tightest device/replica); "
           "headroom under it fires hbm_headroom", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_GOODPUT_MIN", "float", 0.5,
           "goodput-fraction floor; a settled run (steps > 0) under it "
           "fires goodput_drop", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_MIN_HEALTH", "float", 0.5,
           "worst-replica health-score floor; a fleet under it fires "
           "health_collapse", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_COOLDOWN_S", "float", 60.0,
           "minimum seconds an alert stays active before it may "
           "resolve (anti-flap hold)", "alerts", _A_ALERTS),
        _k("TPUFLOW_ALERT_REROUTE_RATE", "float", 0.1,
           "router reroute rate (reroutes / completed requests over "
           "the fast window) past which reroute_spike fires — "
           "sustained rerouting means replicas are dying or stalling "
           "faster than the fleet absorbs", "alerts", _A_ROUTER),
        _k("TPUFLOW_ALERT_ROUTER_TTFT_FRAC", "float", 0.5,
           "fraction of the fleet TTFT p95 the router-side wait per "
           "request (fast window) may reach before "
           "ttft_router_dominance fires — past it, latency lives in "
           "router admission, not the replicas", "alerts", _A_TRACE),
        # ---------------------------------------------------------- trace
        _k("TPUFLOW_TRACE", "bool", True,
           "0 = disarm end-to-end request tracing (context minting, "
           "propagation, and span recording; the disarmed fast path "
           "is one `is not None` check per integration point)",
           "trace", _A_TRACE),
        _k("TPUFLOW_TRACE_SAMPLE", "float", 1.0,
           "head-sample rate for ingress-minted traces (0..1); SLO "
           "breach, reroute, forward error, and queue timeout always "
           "record regardless (tail sampling)", "trace", _A_TRACE),
        _k("TPUFLOW_TRACE_DIR", "path", None,
           "trace-span JSONL directory override (default: "
           "<obs_dir>/trace beside the recorder's event fragments; "
           "unset with telemetry off = spans counted dropped)",
           "trace", _A_TRACE, default_doc="unset"),
        # -------------------------------------------------------- testing
        _k("TPUFLOW_FAULT", "str", None,
           "comma-separated fault-injection specs (chaos suite)",
           "testing", _A_FLOW),
        _k("TPUFLOW_CRASH_SENTINEL", "path", None,
           "sentinel file chaos tests use to fire a crash exactly once",
           "testing", _A_FLOW, internal=True),
        _k("TPUFLOW_TEST_CKPT_DIR", "path", None,
           "checkpoint dir chaos-test gang snippets inherit", "testing",
           _A_FLOW, internal=True),
        _k("TPUFLOW_HEARTBEAT_FILE", "path", None,
           "member heartbeat file the supervisor assigns", "flow",
           _A_FLOW, internal=True),
        # ---------------------------------------------------------- bench
        _k("TPUFLOW_BENCH_TRAIN", "bool", True,
           "0 = skip bench train legs", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_TRAIN_TIMEOUT", "float", 480.0,
           "bench train-leg subprocess timeout (s)", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_SERVE", "bool", True,
           "0 = skip the serving bench leg", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_ROUTER", "bool", True,
           "0 = skip the serving.router bench leg (3 in-process "
           "replicas + one kill behind the front door; records "
           "dropped_requests — must be 0 — and routed p99)", "bench",
           _A_BENCH),
        _k("TPUFLOW_BENCH_DISAGG", "bool", True,
           "0 = skip the serving.disagg bench leg (TTFT cold vs "
           "tier-hit vs cross-engine ship on one hot prompt set; "
           "records ttft_tier_hit_vs_cold — fresh on-chip gate < 1.0 "
           "— per-tier hit rates, and ship exactness)", "bench",
           _A_BENCH),
        _k("TPUFLOW_BENCH_INT8", "bool", True,
           "0 = skip the int8 bench legs", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_OVERLAP", "bool", True,
           "0 = skip the save-overlap bench leg", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_OVERLAP_GB", "float", 3.4,
           "save-overlap bench payload (GiB)", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_DISK", "bool", True,
           "0 = skip the disk-ceiling bench probe", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_DISK_DIR", "path", None,
           "disk-ceiling probe directory", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_DEVICE", "bool", False,
           "1 = bench device-sharded checkpoint IO", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_DEVICES", "int", 8,
           "device shards for the device-IO bench", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_GB", "float", 1.0,
           "device-IO bench payload (GiB)", "bench", _A_BENCH),
        _k("TPUFLOW_BENCH_DIR", "path", None,
           "bench scratch/output directory", "bench", _A_BENCH),
        # ------------------------------------------------------------ e2e
        _k("TPUFLOW_E2E_ALLOW_CPU", "bool", False,
           "1 = let tools/e2e_tpu.py run on CPU", "e2e", _A_BENCH),
        _k("TPUFLOW_E2E_GPT_PRESET", "str", "gpt2",
           "e2e GPT preset", "e2e", _A_BENCH),
        _k("TPUFLOW_E2E_GPT_SEQ", "int", 512,
           "e2e GPT sequence length", "e2e", _A_BENCH),
        _k("TPUFLOW_E2E_GPT_DATA_AXIS", "int", 1,
           "e2e GPT data-axis width", "e2e", _A_BENCH),
        _k("TPUFLOW_E2E_GPT_FSDP_AXIS", "int", 1,
           "e2e GPT fsdp-axis width", "e2e", _A_BENCH),
        # ---------------------------------------------------------- flows
        _k("TPUFLOW_STORAGE", "path", "/tmp/tpuflow_run",
           "checkpoint storage path the example custom-Trainer flow "
           "uses", "flow", _A_SETUP),
    )
)

# The operator-facing subsystem tables, in README order.
_SUBSYSTEM_TITLES = (
    ("flow", "Flow orchestration & gangs"),
    ("dist", "Distributed runtime"),
    ("train", "Training step pipeline"),
    ("data", "Data"),
    ("ckpt", "Checkpointing & preemption"),
    ("obs", "Observability"),
    ("health", "Training health"),
    ("ops", "Kernels & dispatch"),
    ("quant", "Quantization"),
    ("serve", "Serving"),
    ("kv", "Disaggregated serving & KV tiers"),
    ("fleet", "Fleet observatory"),
    ("router", "Front-door router"),
    ("trace", "Distributed tracing"),
    ("device", "Device observatory"),
    ("alerts", "Run registry & alerting"),
    ("testing", "Fault injection & testing"),
    ("bench", "Benchmark"),
    ("e2e", "On-chip e2e"),
)

MARKDOWN_BEGIN = (
    "<!-- BEGIN KNOB TABLES (generated by "
    "`python -m tpuflow.utils.knobs --markdown`; edit the registry in "
    "tpuflow/utils/knobs.py, then regenerate — tools/tpulint.py pass 1 "
    "fails on drift) -->"
)
MARKDOWN_END = "<!-- END KNOB TABLES -->"


# ------------------------------------------------------------- accessors
def _declared(name: str) -> Knob:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"undeclared knob {name!r}: every TPUFLOW_* env knob must be "
            "declared in tpuflow/utils/knobs.py REGISTRY (this is how "
            "typo'd names die loudly instead of silently defaulting — "
            "tools/tpulint.py pass 1 enforces the same contract "
            "statically)"
        ) from None


def raw(name: str, default: str | None = None) -> str | None:
    """``os.environ.get`` with a declaration check — the migration
    workhorse for call sites whose parsing conventions (malformed-value
    fallbacks, bespoke truthiness sets) are pinned by tests."""
    _declared(name)
    return os.environ.get(name, default)


def is_set(name: str) -> bool:
    _declared(name)
    return name in os.environ


def get_str(name: str, default=_UNSET):
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None:
        return knob.default if default is _UNSET else default
    return val


def get_int(name: str, default=_UNSET):
    """Typed read; unset → registry default (or the call-site override).
    A malformed value raises ``ValueError`` naming the knob — same
    failure the bare ``int(os.environ[...])`` sites always had, now with
    a useful message."""
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None or val == "":
        return knob.default if default is _UNSET else default
    try:
        return int(val)
    except ValueError:
        raise ValueError(f"{name}={val!r} is not an integer") from None


def get_float(name: str, default=_UNSET):
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None or val == "":
        return knob.default if default is _UNSET else default
    try:
        return float(val)
    except ValueError:
        raise ValueError(f"{name}={val!r} is not a number") from None


def get_int_lenient(name: str, default=_UNSET):
    """Like :func:`get_int` but a malformed value falls back to the
    default instead of raising — the convention of knobs that must never
    kill a run mid-provisioning on a typo (dispatch depth, prefetch
    depth, checkpoint IO retries; their fallbacks are pinned by tests)."""
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None or val == "":
        return knob.default if default is _UNSET else default
    try:
        return int(val)
    except ValueError:
        return knob.default if default is _UNSET else default


def get_float_lenient(name: str, default=_UNSET):
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None or val == "":
        return knob.default if default is _UNSET else default
    try:
        return float(val)
    except ValueError:
        return knob.default if default is _UNSET else default


_FALSY = ("0", "false", "off", "no")


def get_bool(name: str, default=_UNSET):
    """Truthy unless ``0/false/off/no`` (case-insensitive) — the
    convention the comm-overlap knobs pinned in tests. Sites with a
    narrower falsy set read through :func:`raw` instead."""
    knob = _declared(name)
    val = os.environ.get(name)
    if val is None:
        return knob.default if default is _UNSET else default
    return val.strip().lower() not in _FALSY


# ------------------------------------------------------------- markdown
def _table(rows: list[Knob]) -> list[str]:
    out = [
        "| Knob | Type | Default | What it does |",
        "| --- | --- | --- | --- |",
    ]
    for k in rows:
        typ = k.type
        if k.type == "enum" and k.choices:
            typ = " \\| ".join(k.choices)
        out.append(
            f"| `{k.name}` | {typ} | `{k.shown_default}` | {k.doc} "
            f"([runbook](#{k.anchor})) |"
        )
    return out


def markdown() -> str:
    """The generated README knob-reference region (between the
    ``KNOB TABLES`` markers), one table per subsystem plus the internal
    plumbing table."""
    lines = [MARKDOWN_BEGIN, ""]
    lines.append(
        f"{len(REGISTRY)} knobs are declared in "
        "`tpuflow/utils/knobs.py`; every `TPUFLOW_*` read anywhere in "
        "the tree goes through its typed accessors "
        "(`tools/tpulint.py` pass 1). Regenerate this section with "
        "`python -m tpuflow.utils.knobs --markdown`."
    )
    for sub, title in _SUBSYSTEM_TITLES:
        rows = [
            k for k in REGISTRY.values()
            if k.subsystem == sub and not k.internal
        ]
        if not rows:
            continue
        lines += ["", f"### {title} knobs", ""]
        lines += _table(sorted(rows, key=lambda k: k.name))
    internal = [k for k in REGISTRY.values() if k.internal]
    lines += [
        "",
        "### Internal plumbing (not operator knobs)",
        "",
        "Stamped by the supervisor/launcher/tests and read back by "
        "members — set them by hand and the runbooks above stop "
        "describing your system.",
        "",
        "| Knob | Stamped by | Meaning |",
        "| --- | --- | --- |",
    ]
    for k in sorted(internal, key=lambda k: k.name):
        lines.append(f"| `{k.name}` | {k.subsystem} | {k.doc} |")
    lines += ["", MARKDOWN_END]
    return "\n".join(lines)


def readme_region(readme_text: str) -> str | None:
    """The current generated region in ``readme_text`` (markers
    inclusive), or None when the markers are absent/torn."""
    try:
        start = readme_text.index(MARKDOWN_BEGIN)
        end = readme_text.index(MARKDOWN_END) + len(MARKDOWN_END)
    except ValueError:
        return None
    if end <= start:
        return None
    return readme_text[start:end]


def check_readme(readme_path: str) -> list[str]:
    """Error strings when the README's generated region is missing or
    stale (``--check``; tpulint pass 1 calls this)."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError as e:
        return [f"cannot read {readme_path}: {e}"]
    region = readme_region(text)
    if region is None:
        return [
            f"{readme_path}: knob-table markers not found — paste the "
            "output of `python -m tpuflow.utils.knobs --markdown` into "
            "the README"
        ]
    if region != markdown():
        return [
            f"{readme_path}: knob tables are stale — regenerate with "
            "`python -m tpuflow.utils.knobs --markdown` (registry and "
            "README must agree byte-for-byte)"
        ]
    return []


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="TPUFLOW_* knob registry: emit or verify the README "
        "knob tables"
    )
    p.add_argument("--markdown", action="store_true",
                   help="print the generated README knob-table region")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when the README region is stale")
    p.add_argument("--list", action="store_true",
                   help="one line per declared knob")
    p.add_argument("--readme", default=None,
                   help="README path (default: repo root README.md)")
    args = p.parse_args(argv)
    readme = args.readme or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "README.md",
    )
    if args.list:
        for k in sorted(REGISTRY.values(), key=lambda k: k.name):
            flag = " [internal]" if k.internal else ""
            print(f"{k.name}  ({k.type}, default {k.shown_default})"
                  f"{flag} — {k.doc}")
        return 0
    if args.check:
        errors = check_readme(readme)
        for e in errors:
            print(f"[knobs] ERROR: {e}")
        if not errors:
            print(f"[knobs] ok ({len(REGISTRY)} knobs, README in sync)")
        return 1 if errors else 0
    if args.markdown:
        print(markdown())
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
