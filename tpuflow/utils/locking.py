"""Inter-process file lock (fcntl) for shared-filesystem races.

Parity: the reference guards its dataset download with
``FileLock(os.path.expanduser("~/data.lock"))`` so N co-located workers don't
concurrently download/extract into the same directory
(my_ray_module.py:10,41,54). Same pattern here, on fcntl so it needs no
third-party package.
"""

from __future__ import annotations

import fcntl
import os


class FileLock:
    """``with FileLock(path):`` — exclusive advisory lock on ``path``."""

    def __init__(self, path: str):
        self.path = os.path.expanduser(path)
        self._fd: int | None = None

    def __enter__(self) -> "FileLock":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc) -> None:
        assert self._fd is not None
        fcntl.flock(self._fd, fcntl.LOCK_UN)
        os.close(self._fd)
        self._fd = None
