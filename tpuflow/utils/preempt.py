"""Preemption primitives shared by the flow and train layers.

Podracer-style gang-scheduled TPU architectures treat preemption as
routine, not exceptional (PAPERS.md): the infrastructure SIGTERMs a host,
the training loop drains a final checkpoint at the next step boundary,
and the process exits with a *requeue* code the supervisor distinguishes
from a crash — the step reruns without consuming the retry budget (and,
deployed, without consuming the k8s Job ``backoffLimit``; see
tpuflow.flow.deploy).

This module is dependency-free on purpose: the flow runner and the gang
bootstrap import it without pulling in jax/flax, while the train loops
re-export the checking API from ``tpuflow.train.step``.
"""

from __future__ import annotations

import signal
import threading
import time

from tpuflow.utils import knobs

# BSD EX_TEMPFAIL: "try again later". Distinct from every exit code a crash
# produces (Python exceptions → 1, signals → 128+N / negative), so the gang
# supervisor can classify a member's death as requeue-not-failure.
REQUEUE_EXIT_CODE = 75


class Preempted(RuntimeError):
    """Raised by a train loop at a step boundary after it drained and
    committed its final checkpoint; the gang bootstrap converts it into a
    ``REQUEUE_EXIT_CODE`` process exit."""


def launch_attempt() -> int:
    """Which launch of the current step this process belongs to (0 = first
    try; retries and requeues increment). Stamped into ``TPUFLOW_ATTEMPT``
    by the gang launcher. Train loops use it to switch checkpointing from
    overlap-optimal to durability-optimal on retried attempts: an async
    multi-host save only *commits* at the next drain point, so a
    deterministic crash at step K would otherwise die before step K ever
    commits — every retry restarts at the same step and the retry budget
    burns with zero forward progress (a livelock, observed end-to-end).
    Draining eagerly on retries makes each completed step durable before
    the crashing one reruns."""
    import os

    try:
        return int(knobs.raw("TPUFLOW_ATTEMPT", "0") or 0)
    except ValueError:
        return 0


_FLAG = threading.Event()
# Monotonic timestamp of the FIRST preemption request this cycle: the
# anchor the remaining-grace estimate counts down from. Guarded by the
# GIL-atomicity of a single assignment (the signal handler may run on any
# thread's behalf).
_REQUESTED_AT: float | None = None


def request_preemption(signum=None, frame=None) -> None:
    """Mark this process preempted (signal-handler compatible signature).
    Checked by the train loops at step boundaries; idempotent — repeated
    SIGTERMs keep the original grace anchor."""
    global _REQUESTED_AT
    if _REQUESTED_AT is None:
        _REQUESTED_AT = time.monotonic()
    _FLAG.set()


def preemption_requested() -> bool:
    return _FLAG.is_set()


def clear_preemption() -> None:
    global _REQUESTED_AT
    _REQUESTED_AT = None
    _FLAG.clear()


def grace_budget_s(default: float = 30.0) -> float:
    """Total termination grace this process believes it has after SIGTERM,
    in seconds (``TPUFLOW_PREEMPT_GRACE_S``). The gang launcher stamps it
    from the supervisor's kill grace; deployed, it should mirror the pod's
    ``terminationGracePeriodSeconds``. Malformed values fall back to
    ``default``."""
    import os

    env = knobs.raw("TPUFLOW_PREEMPT_GRACE_S")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    return default


def grace_remaining_s() -> float | None:
    """Estimated termination grace still left, or None when no preemption
    is in flight. Never negative — a drain that started late sees 0 and
    must take the fastest path it has."""
    anchor = _REQUESTED_AT
    if anchor is None or not _FLAG.is_set():
        return None
    return max(0.0, grace_budget_s() - (time.monotonic() - anchor))


def emergency_save_advised(threshold_default: float = 10.0) -> bool:
    """True when a preemption is in flight and the estimated remaining
    grace is under ``TPUFLOW_PREEMPT_EMERGENCY_S`` (default 10 s): drain
    points should then write the fast local-tier emergency checkpoint
    (``CheckpointManager.emergency_save`` — commit without the persistent
    upload) instead of the full save, so the last steps of progress land
    on *some* durable tier before the SIGKILL."""
    import os

    remaining = grace_remaining_s()
    if remaining is None:
        return False
    env = knobs.raw("TPUFLOW_PREEMPT_EMERGENCY_S")
    threshold = threshold_default
    if env:
        try:
            threshold = float(env)
        except ValueError:
            pass
    return remaining < threshold


def install_sigterm_handler() -> bool:
    """Route SIGTERM to ``request_preemption``. Main-thread only (signal
    module restriction) — returns False instead of raising elsewhere, so
    library code may call it opportunistically."""
    try:
        signal.signal(signal.SIGTERM, request_preemption)
        return True
    except ValueError:  # not the main thread
        return False
